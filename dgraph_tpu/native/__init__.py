"""ctypes bindings for the native C++ runtime (native/native.cc).

The compute path is JAX/XLA/Pallas; the runtime around it — storage
engine (KV + WAL + snapshots, the Badger/raftwal role: posting/mvcc.go,
raftwal/storage.go in the reference), the group-varint UID codec
(codec/codec.go), and string-match kernels (worker/match.go) — is C++.

The shared library is built on first import (g++ is part of the
toolchain); if the build fails, `available()` is False and pure-Python
fallbacks in the calling modules take over, so the framework degrades
rather than breaks on odd toolchains.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SO = os.path.join(_REPO, "native", "build", "libdgraph_native.so")

_lib = None
_lock = threading.Lock()
_tried = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", os.path.join(_REPO, "native")],
                           capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def _stale() -> bool:
    """A prebuilt .so older than the source misses newer symbols and
    would crash symbol binding below — rebuild instead of loading it."""
    src = os.path.join(_REPO, "native", "native.cc")
    try:
        return os.path.getmtime(_SO) < os.path.getmtime(src)
    except OSError:
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if (not os.path.exists(_SO) or _stale()) and not _build():
            if not os.path.exists(_SO):
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        try:
            _bind(lib)
        except AttributeError:
            # missing symbol despite the staleness check (e.g. a
            # hand-copied .so): degrade to the pure-Python fallbacks
            # instead of poisoning every import
            return None
        _lib = lib
        return _lib


def _bind(lib):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.dgt_kv_open.restype = ctypes.c_void_p
        lib.dgt_kv_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dgt_kv_put.restype = ctypes.c_int
        lib.dgt_kv_put.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32,
                                   u8p, ctypes.c_uint32]
        lib.dgt_kv_del.restype = ctypes.c_int
        lib.dgt_kv_del.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32]
        lib.dgt_kv_get.restype = ctypes.c_int64
        lib.dgt_kv_get.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32,
                                   u8p, ctypes.c_uint64]
        lib.dgt_kv_count.restype = ctypes.c_uint64
        lib.dgt_kv_count.argtypes = [ctypes.c_void_p]
        lib.dgt_kv_set_memtable.restype = None
        lib.dgt_kv_set_memtable.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint64]
        lib.dgt_kv_flush.restype = ctypes.c_int
        lib.dgt_kv_flush.argtypes = [ctypes.c_void_p]
        lib.dgt_kv_snapshot.restype = ctypes.c_int
        lib.dgt_kv_snapshot.argtypes = [ctypes.c_void_p]
        lib.dgt_kv_close.restype = None
        lib.dgt_kv_close.argtypes = [ctypes.c_void_p]
        lib.dgt_kv_iter.restype = ctypes.c_void_p
        lib.dgt_kv_iter.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32]
        lib.dgt_kv_iter_next.restype = ctypes.c_int
        lib.dgt_kv_iter_next.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_uint64, u64p,
            u8p, ctypes.c_uint64, u64p]
        lib.dgt_kv_iter_close.restype = None
        lib.dgt_kv_iter_close.argtypes = [ctypes.c_void_p]
        lib.dgt_wal_open.restype = ctypes.c_void_p
        lib.dgt_wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dgt_wal_append.restype = ctypes.c_int
        lib.dgt_wal_append.argtypes = [ctypes.c_void_p, u8p,
                                       ctypes.c_uint64]
        lib.dgt_wal_flush.restype = ctypes.c_int
        lib.dgt_wal_flush.argtypes = [ctypes.c_void_p]
        lib.dgt_wal_replay.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.dgt_wal_replay.argtypes = [ctypes.c_void_p, u64p, u64p]
        lib.dgt_wal_truncate.restype = ctypes.c_int
        lib.dgt_wal_truncate.argtypes = [ctypes.c_void_p]
        lib.dgt_wal_close.restype = None
        lib.dgt_wal_close.argtypes = [ctypes.c_void_p]
        lib.dgt_free.restype = None
        lib.dgt_free.argtypes = [ctypes.c_void_p]
        lib.dgt_gv_encode.restype = ctypes.c_int64
        lib.dgt_gv_encode.argtypes = [u64p, ctypes.c_uint64, u8p]
        lib.dgt_gv_decode.restype = ctypes.c_int64
        lib.dgt_gv_decode.argtypes = [u8p, ctypes.c_uint64, u64p]
        lib.dgt_gv_count.restype = ctypes.c_uint64
        lib.dgt_gv_count.argtypes = [u8p, ctypes.c_uint64]
        lib.dgt_levenshtein.restype = ctypes.c_int32
        lib.dgt_levenshtein.argtypes = [u8p, ctypes.c_uint32, u8p,
                                        ctypes.c_uint32, ctypes.c_int32]
        lib.dgt_match_mask.restype = ctypes.c_int
        lib.dgt_match_mask.argtypes = [
            u8p, ctypes.c_uint32, ctypes.c_int32, u8p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, u8p]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.dgt_match_mask_idx.restype = ctypes.c_int
        lib.dgt_match_mask_idx.argtypes = [
            u8p, ctypes.c_uint32, ctypes.c_int32, u8p,
            i64p, i64p, ctypes.c_int64, u8p]
        lib.dgt_merge_count.restype = ctypes.c_int
        lib.dgt_merge_count.argtypes = [
            u64p, i64p, ctypes.c_int64, ctypes.c_int64, u64p, i64p]
        lib.dgt_tokenize_batch.restype = ctypes.c_int
        lib.dgt_tokenize_batch.argtypes = [
            u8p, u64p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint8, ctypes.c_uint8, ctypes.c_uint8,
            ctypes.c_uint8,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            u64p,
            ctypes.POINTER(u64p), u64p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)), u64p,
            ctypes.POINTER(u64p)]
        lib.dgt_rdf_parse.restype = ctypes.c_int
        lib.dgt_rdf_parse.argtypes = [
            u8p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)), u64p]
        lib.dgt_json_rows.restype = ctypes.c_int
        lib.dgt_json_rows.argtypes = [
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64)]


def available() -> bool:
    return _load() is not None


# Build eagerly at import (cached after the first build) so the compile
# cost never lands inside a query loop or engine open.
_load()


def _buf(b: bytes):
    return ctypes.cast(ctypes.create_string_buffer(b, len(b) or 1),
                       ctypes.POINTER(ctypes.c_uint8))


class NativeKV:
    """Ordered KV store with WAL durability + snapshot compaction.
    Crash recovery = snapshot load + WAL replay with torn-tail truncate
    (the contract Badger provides the reference)."""
    # dglint: guarded-by=*:external (the native layer has its own
    # internal locking for reads; writes arrive only on the engine's
    # serialized write path — Python-side handle state is set once in
    # __init__ and cleared only at close)

    def __init__(self, directory: str, sync: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dgt_kv_open(directory.encode(), 1 if sync else 0)
        if not self._h:
            raise OSError(f"cannot open native kv store at {directory}")

    def put(self, key: bytes, val: bytes):
        if self._lib.dgt_kv_put(self._h, _buf(key), len(key),
                                _buf(val), len(val)) != 0:
            raise OSError("kv put failed")

    def delete(self, key: bytes):
        if self._lib.dgt_kv_del(self._h, _buf(key), len(key)) != 0:
            raise OSError("kv del failed")

    def get(self, key: bytes):
        # size-probe + copy are separate store calls; retry if a
        # concurrent writer grew the value in between.
        n = self._lib.dgt_kv_get(self._h, _buf(key), len(key), None, 0)
        while True:
            if n < 0:
                return None
            out = (ctypes.c_uint8 * max(n, 1))()
            m = self._lib.dgt_kv_get(self._h, _buf(key), len(key), out, n)
            if m < 0:
                return None
            if m <= n:
                return bytes(out[:m])
            n = m

    def __len__(self):
        return self._lib.dgt_kv_count(self._h)

    def scan(self, prefix: bytes = b""):
        """Yields (key, value) over a stable snapshot, key-ordered."""
        it = self._lib.dgt_kv_iter(self._h, _buf(prefix), len(prefix))
        try:
            klen = ctypes.c_uint64()
            vlen = ctypes.c_uint64()
            while self._lib.dgt_kv_iter_next(
                    it, None, 0, ctypes.byref(klen),
                    None, 0, ctypes.byref(vlen)) == 0:
                kout = (ctypes.c_uint8 * max(klen.value, 1))()
                vout = (ctypes.c_uint8 * max(vlen.value, 1))()
                self._lib.dgt_kv_iter_next(
                    it, kout, klen.value, ctypes.byref(klen),
                    vout, vlen.value, ctypes.byref(vlen))
                yield bytes(kout[:klen.value]), bytes(vout[:vlen.value])
        finally:
            self._lib.dgt_kv_iter_close(it)

    def flush(self):
        self._lib.dgt_kv_flush(self._h)

    def snapshot(self):
        """Durability point: flush the memtable to a run and fully
        compact the runs into one, truncating the WAL (the LSM's
        replacement for the old whole-store SNAPSHOT dump)."""
        if self._lib.dgt_kv_snapshot(self._h) != 0:
            raise OSError("kv snapshot failed")

    def set_memtable(self, nbytes: int):
        """Lower/raise the memtable flush threshold (default 64MB, or
        DGT_KV_MEMTABLE_BYTES at open)."""
        self._lib.dgt_kv_set_memtable(self._h, nbytes)

    def close(self):
        if self._h:
            self._lib.dgt_kv_close(self._h)
            self._h = None


class NativeWal:
    """Append-only CRC-framed record log (the raftwal/storage.go role)."""

    def __init__(self, path: str, sync: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.dgt_wal_open(path.encode(), 1 if sync else 0)
        if not self._h:
            from dgraph_tpu.storage.wal import raise_if_legacy_wal
            raise_if_legacy_wal(path)
            raise OSError(f"cannot open wal at {path}")

    def append(self, payload: bytes):
        if self._lib.dgt_wal_append(self._h, _buf(payload),
                                    len(payload)) != 0:
            raise OSError("wal append failed")

    def flush(self):
        self._lib.dgt_wal_flush(self._h)

    def replay(self):
        """All valid records in order (truncates any torn tail)."""
        total = ctypes.c_uint64()
        count = ctypes.c_uint64()
        buf = self._lib.dgt_wal_replay(self._h, ctypes.byref(total),
                                       ctypes.byref(count))
        records = []
        if buf and total.value:
            raw = ctypes.string_at(buf, total.value)
            off = 0
            for _ in range(count.value):
                ln = int.from_bytes(raw[off:off + 8], "little")
                records.append(raw[off + 8: off + 8 + ln])
                off += 8 + ln
        if buf:
            self._lib.dgt_free(buf)
        return records

    def truncate(self):
        if self._lib.dgt_wal_truncate(self._h) != 0:
            raise OSError("wal truncate failed")

    def close(self):
        if self._h:
            self._lib.dgt_wal_close(self._h)
            self._h = None


def gv_encode(uids) -> bytes:
    """Sorted uint64 array -> group-varint delta stream."""
    import numpy as np
    lib = _load()
    a = np.ascontiguousarray(np.asarray(uids, dtype=np.uint64))
    cap = 16 + len(a) * 9
    out = (ctypes.c_uint8 * cap)()
    n = lib.dgt_gv_encode(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(a), out)
    if n < 0:
        raise ValueError("gv encode failed")
    return bytes(out[:n])


def gv_decode(buf: bytes):
    """group-varint delta stream -> uint64 numpy array."""
    import numpy as np
    lib = _load()
    n = lib.dgt_gv_count(_buf(buf), len(buf))
    out = np.empty(int(n), dtype=np.uint64)
    got = lib.dgt_gv_decode(_buf(buf), len(buf),
                            out.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_uint64)))
    if got < 0:
        raise ValueError("gv decode: malformed stream")
    return out[:got]


def levenshtein(a: str, b: str, max_d: int) -> int:
    """Bounded edit distance; > max_d reported as max_d + 1."""
    lib = _load()
    ab = a.encode("utf-8", "surrogatepass")
    bb = b.encode("utf-8", "surrogatepass")
    return lib.dgt_levenshtein(_buf(ab), len(ab), _buf(bb), len(bb),
                               max_d)


# column type tags for json_rows (mirror native.cc dgt_json_rows)
JCOL_INT = 0
JCOL_FLOAT = 1
JCOL_BOOL = 2
JCOL_STR = 3
JCOL_UID = 4


def json_rows(n_rows: int, cols) -> "bytes | None":
    """Serialize typed columns into a JSON array of row objects — the
    query-result fast path (ref query/outputnode.go fastJsonNode, a
    documented reference hot loop). `cols` is a list of
    (name: str, type: JCOL_*, data: np.ndarray, offsets: np.ndarray
    | None, present: np.ndarray(uint8) | None). Returns the serialized
    bytes, or None when the native runtime is unavailable (callers
    fall back to dict + json.dumps)."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    n_cols = len(cols)
    names = (ctypes.c_char_p * n_cols)()
    types = (ctypes.c_int32 * n_cols)()
    data = (ctypes.c_void_p * n_cols)()
    offsets = (ctypes.POINTER(ctypes.c_int64) * n_cols)()
    present = (ctypes.POINTER(ctypes.c_uint8) * n_cols)()
    keep = []  # hold refs so buffers outlive the call
    for i, (name, t, d, off, pres) in enumerate(cols):
        nb = name.encode("utf-8")
        keep.append(nb)
        names[i] = nb
        types[i] = t
        d = np.ascontiguousarray(d)
        keep.append(d)
        data[i] = d.ctypes.data_as(ctypes.c_void_p)
        if off is not None:
            off = np.ascontiguousarray(off, dtype=np.int64)
            keep.append(off)
            offsets[i] = off.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64))
        if pres is not None:
            pres = np.ascontiguousarray(pres, dtype=np.uint8)
            keep.append(pres)
            present[i] = pres.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8))
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_uint64()
    rc = lib.dgt_json_rows(n_rows, n_cols, names, types, data, offsets,
                           present, ctypes.byref(out),
                           ctypes.byref(out_len))
    if rc != 0:
        return None
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.dgt_free(out)


def match_mask(term_lower: bytes, max_d: int, blob, offsets) -> "object":
    """Batched fuzzy-match verify: uint8 mask per value (1 = within
    max_d of the pre-lowercased term, 0 = no, 2 = non-ASCII value the
    caller must re-verify with Python lowercasing). None when the
    native runtime is unavailable."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    blob = np.ascontiguousarray(blob, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    out = np.zeros(max(n, 1), np.uint8)
    lib.dgt_match_mask(
        _buf(term_lower), len(term_lower), max_d,
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out[:n]


def match_mask_idx(term_lower: bytes, max_d: int, blob, offsets,
                   idx) -> "object":
    """match_mask over SELECTED rows of a cached whole-column payload
    blob; None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    blob = np.ascontiguousarray(blob, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    n = len(idx)
    out = np.zeros(max(n, 1), np.uint8)
    lib.dgt_match_mask_idx(
        _buf(term_lower), len(term_lower), max_d,
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out[:n]


def merge_count(buckets: "list", need: int) -> "object":
    """uids appearing in >= need of the given SORTED uid buckets, via
    one k-way linear merge (no concatenate+sort). None when native is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    offs = np.zeros(len(buckets) + 1, np.int64)
    np.cumsum([len(b) for b in buckets], out=offs[1:])
    total = int(offs[-1])
    if total == 0:
        return np.empty(0, np.uint64)
    vals = np.empty(total, np.uint64)
    for i, b in enumerate(buckets):
        vals[offs[i]:offs[i + 1]] = b
    out = np.empty(total, np.uint64)
    out_n = ctypes.c_int64(0)
    rc = lib.dgt_merge_count(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(buckets), need,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.byref(out_n))
    if rc != 0:
        return None
    return out[:out_n.value].copy()


# dgt_tokenize_batch mode bits (mirror native.cc)
TOK_TERM = 1
TOK_TRIGRAM = 2
TOK_FULLTEXT_EN = 4
TOK_EXACT = 8


def tokenize_batch(payload, offsets, mode: int, idents) -> "object":
    """Batched ASCII tokenization for index builds (ref tok/tok.go
    built-in tokenizers; native.cc dgt_tokenize_batch).  `payload` is
    the concatenated utf-8 (ASCII-only) values, `offsets` a uint64
    array of n+1 boundaries, `idents` the (term, trigram, fulltext,
    exact) identifier bytes.  Returns (tokens: list[bytes] with ident
    prefixes, groups: list[np.uint32 value-index arrays]); tokens are
    UNIQUE and each group is ascending, but the token list is NOT
    globally sorted (short-packed tokens precede long ones — the C
    sort runs per partition).  None when the native runtime is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    payload = np.ascontiguousarray(payload, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
    n = len(offsets) - 1
    u8pp = ctypes.POINTER(ctypes.c_uint8)
    u64pp = ctypes.POINTER(ctypes.c_uint64)
    tok_out = u8pp()
    tok_len = ctypes.c_uint64()
    tok_offs = u64pp()
    n_toks = ctypes.c_uint64()
    val_idx = ctypes.POINTER(ctypes.c_uint32)()
    n_pairs = ctypes.c_uint64()
    bounds = u64pp()
    rc = lib.dgt_tokenize_batch(
        payload.ctypes.data_as(u8pp),
        offsets.ctypes.data_as(u64pp),
        n, mode, idents[0], idents[1], idents[2], idents[3],
        ctypes.byref(tok_out), ctypes.byref(tok_len),
        ctypes.byref(tok_offs), ctypes.byref(n_toks),
        ctypes.byref(val_idx), ctypes.byref(n_pairs),
        ctypes.byref(bounds))
    if rc != 0:
        return None
    try:
        nt = n_toks.value
        npair = n_pairs.value
        toks_b = ctypes.string_at(tok_out, tok_len.value)
        offs = np.ctypeslib.as_array(tok_offs, shape=(nt + 1,)).copy()
        bnds = np.ctypeslib.as_array(bounds, shape=(nt + 1,)).copy()
        vidx = np.ctypeslib.as_array(
            val_idx, shape=(max(npair, 1),))[:npair].copy()
        tokens = [toks_b[offs[i]:offs[i + 1]] for i in range(nt)]
        groups = [vidx[bnds[i]:bnds[i + 1]] for i in range(nt)]
        return tokens, groups
    finally:
        lib.dgt_free(tok_out)
        lib.dgt_free(tok_offs)
        lib.dgt_free(val_idx)
        lib.dgt_free(bounds)


class ParsedRdf:
    """Columnar result of dgt_rdf_parse (see native.cc blob layout):
    edge rows, literal rows, interned pred/lang/dtype tables, and the
    fallback line spans the python grammar must parse."""

    __slots__ = ("edges", "vals", "fallback", "preds", "langs",
                 "dtypes")

    def __init__(self, edges, vals, fallback, preds, langs, dtypes):
        self.edges = edges        # (subj, pred_id, dst, fac_start, fac_len)
        self.vals = vals          # (subj, pred_id, lit_start, lit_len,
        #                            flags, lang_id, dtype_id,
        #                            fac_start, fac_len)
        self.fallback = fallback  # (start, len) line spans
        self.preds = preds
        self.langs = langs
        self.dtypes = dtypes


def rdf_parse(text: bytes) -> "ParsedRdf | None":
    """Parse an N-Quad text chunk natively; None when the runtime is
    unavailable.  Lines outside the fast grammar come back as spans in
    .fallback — the caller routes them through gql.nquad.parse_rdf."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np
    blob_p = ctypes.POINTER(ctypes.c_uint8)()
    blob_len = ctypes.c_uint64()
    buf = np.frombuffer(text, np.uint8)
    rc = lib.dgt_rdf_parse(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(text),
        ctypes.byref(blob_p), ctypes.byref(blob_len))
    if rc != 0:
        return None
    try:
        raw = np.frombuffer(
            ctypes.string_at(blob_p, blob_len.value), np.uint64)
        n_e, n_v, n_fb, n_p, n_l, n_d, pb, lb, db = raw[:9].tolist()
        o = 9

        def take(n):
            nonlocal o
            a = raw[o:o + n]
            o += n
            return a

        edges = tuple(take(n_e) for _ in range(5))
        vals = tuple(take(n_v) for _ in range(9))
        fallback = (take(n_fb), take(n_fb))

        def table(n, nbytes):
            nonlocal o
            offs = take(n + 1)
            bview = raw[o:o + (nbytes + 7) // 8].tobytes()[:nbytes]
            o += (nbytes + 7) // 8
            return [bview[offs[i]:offs[i + 1]].decode("utf-8")
                    for i in range(n)]

        preds = table(n_p, pb)
        langs = table(n_l, lb)
        dtypes = table(n_d, db)
        return ParsedRdf(edges, vals, fallback, preds, langs, dtypes)
    finally:
        lib.dgt_free(blob_p)
