"""Host-side storage: MVCC tablets, write-ahead log, rollups.

The reference stores posting lists in Badger with an immutable layer +
ts-keyed mutation deltas (posting/list.go:70, posting/mvcc.go). Here each
predicate is a `Tablet`: a rolled-up base state (host numpy + device
tiles) plus a commit-ts-stamped delta overlay, with rollups folding the
overlay forward — same MVCC semantics, re-shaped so the committed state
is always one repack away from dense device tensors.
"""

from dgraph_tpu.storage.tablet import Posting, Tablet
from dgraph_tpu.storage.wal import Wal
