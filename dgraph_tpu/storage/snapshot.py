"""Store snapshots: serialize a GraphDB's rolled-up state.

The analogue of the reference bulk loader's output (a ready Badger p/
directory, bulk/reduce.go writing SSTs) and the base artifact for
backup/restore (ee/backup/) and Raft InstallSnapshot payloads
(worker/snapshot.go doStreamSnapshot/populateSnapshot). Format: a
wire-encoded payload of schema text + per-tablet base arrays +
coordinator counters; the file form is gzip-compressed with a magic
header.
"""

from __future__ import annotations

import gzip
import os


def _load_payload(blob: bytes):
    """Wire-encoded (version byte 0x01); files written before the wire
    format existed fall back to wire.loads_compat, the one migration
    shim."""
    from dgraph_tpu import wire
    return wire.loads_compat(blob)

SNAPSHOT_MAGIC = b"DGTPU-SNAP-1"


def _gv_dict(d: dict) -> dict:
    """{key -> sorted uint64 uids} -> {key -> group-varint stream}:
    the at-rest form of every posting surface (ref codec/codec.go —
    the reference never persists a dense uid list either). Native
    dgt_gv_* when the toolchain built, byte-identical numpy fallback
    otherwise (ops/codec.gv_encode)."""
    from dgraph_tpu.ops.codec import gv_encode
    return {k: gv_encode(v) for k, v in d.items()}


def _ungv_dict(d: dict) -> dict:
    import numpy as np

    from dgraph_tpu.ops.codec import gv_decode
    return {k: np.asarray(gv_decode(v), np.uint64)
            for k, v in d.items()}


def _pack_values(values: dict) -> dict:
    """{src -> [Posting]} -> parallel columns (src array, tid bytes,
    payload list, sparse lang/facet maps). One Posting costs ~8 bytes
    of TLV framing and ~20 µs of generic record decode on the wire;
    value-dominated tablets (the LDBC norm) made the per-Posting walk
    the single largest line item of writing a snapshot, so values
    persist columnar like every other plane. Column order is the
    values-dict walk order — deterministic, and inverted exactly by
    _unpack_values."""
    import numpy as np
    srcs: list[int] = []
    tids = bytearray()
    pays: list = []
    langs: list[tuple[int, str]] = []
    facets: list[tuple[int, dict]] = []
    i = 0
    for src, posts in values.items():
        for p in posts:
            srcs.append(src)
            tids.append(int(p.value.tid))
            pays.append(p.value.value)
            if p.lang:
                langs.append((i, p.lang))
            if p.facets:
                facets.append((i, p.facets))
            i += 1
    return {"src": np.asarray(srcs, np.uint64), "tid": bytes(tids),
            "pay": pays, "lang": langs, "facets": facets}


def _unpack_values(pk: dict) -> dict:
    from dgraph_tpu.models.types import TypeID, Val
    from dgraph_tpu.storage.tablet import Posting
    langs = dict(pk["lang"])
    facets = dict(pk["facets"])
    out: dict[int, list] = {}
    for i, (s, t, v) in enumerate(zip(pk["src"].tolist(),
                                      pk["tid"], pk["pay"])):
        out.setdefault(s, []).append(
            Posting(Val(TypeID(t), v), langs.get(i, ""),
                    facets.get(i, {})))
    return out


def dump_tablet(tab) -> dict:
    """One tablet's state — the single wire shape shared by snapshots,
    backups, tablet moves and the cold-tablet store
    (engine/lazy_tablets). Add new Tablet fields HERE.

    The uid-array planes (edges / reverse / token index) persist
    group-varint delta-compressed — cold tablets stay compressed at
    rest in the KV store at ~2 B/uid instead of dense 8 B/uid, the
    same split the reference keeps in codec/ — and decode on
    materialization (restore_tablet).

    Unfolded overlay deltas ARE included: the rollup watermark can be
    pinned below the newest commits (active txns, pinned snapshot
    readers), and a payload of base arrays alone would silently drop
    those committed writes from snapshots/backups."""
    out = {
        "edges_gv": _gv_dict(tab.edges),
        "reverse_gv": _gv_dict(tab.reverse),
        "values_pk": _pack_values(tab.values),
        "index_gv": _gv_dict(tab.index),
        "edge_facets": tab.edge_facets,
        "base_ts": tab.base_ts,
        "deltas": tab.deltas,
        "max_commit_ts": tab.max_commit_ts,
    }
    # trained quantized ANN index (storage/vecstore.py): ships with
    # the tablet so bulk-loaded / moved / restored tablets boot with
    # their codebooks instead of retraining k-means at first query
    ivf = getattr(tab, "vector_ivf", lambda: None)()
    if ivf is not None:
        from dgraph_tpu.storage.vecstore import ivf_to_payload
        out["vec_ivf"] = ivf_to_payload(ivf)
    return out


def restore_tablet(pred: str, schema, st: dict):
    """Inverse of dump_tablet -> a fresh Tablet. Pre-compression
    payloads (dense "edges"/"reverse"/"index" keys) still restore —
    the one migration seam, same policy as loads_compat."""
    from dgraph_tpu.storage.tablet import Tablet
    tab = Tablet(pred, schema)
    tab.edges = _ungv_dict(st["edges_gv"]) if "edges_gv" in st \
        else st["edges"]
    tab.reverse = _ungv_dict(st["reverse_gv"]) if "reverse_gv" in st \
        else st["reverse"]
    tab.values = _unpack_values(st["values_pk"]) \
        if "values_pk" in st else st["values"]
    tab.index = _ungv_dict(st["index_gv"]) if "index_gv" in st \
        else st["index"]
    tab.edge_facets = st["edge_facets"]
    tab.base_ts = st["base_ts"]
    tab.deltas = list(st.get("deltas", ()))  # absent in old payloads
    tab.max_commit_ts = int(st.get("max_commit_ts", tab.base_ts))
    for ts, _ops in tab.deltas:
        tab.max_commit_ts = max(tab.max_commit_ts, ts)
    if "vec_ivf" in st:
        from dgraph_tpu.storage.vecstore import ivf_from_payload
        tab._vec_ivf = (tab.base_ts, tab.schema,
                        ivf_from_payload(st["vec_ivf"]))
    return tab


def dump_state(db) -> dict:
    """GraphDB -> one picklable state payload at a single ts. Deltas
    fold first where the watermark allows; whatever must stay unfolded
    (active txns / pinned readers hold the watermark) ships inside
    dump_tablet's deltas, so the payload is complete either way."""
    from dgraph_tpu.storage.versions import FORMAT_VERSION
    db.rollup_all(window=0)
    tablets = {pred: dump_tablet(tab)
               for pred, tab in db.tablets.items()}
    return {
        # at-rest format stamp (storage/versions.py): payloads written
        # before the stamp existed carry no key and load as version 0
        # — the pinned legacy contract (tests/test_format_version.py)
        "format_version": FORMAT_VERSION,
        "schema": db.schema.describe_all(),
        "tablets": tablets,
        "max_ts": db.coordinator.max_assigned(),
        "next_uid": db.coordinator._next_uid,
        # replicated-but-undecided cross-group stages: a member
        # installing this snapshot must still be able to apply the
        # xfinalize records that follow it in the log
        "pending_txns": {ts: (list(ops), list(keys))
                         for ts, (ops, keys)
                         in db.pending_txns.items()},
        # moved-away / split-partial tombstones: a member restoring
        # this snapshot must keep answering stale-routed requests
        # with a typed misroute, never silently-partial rows
        "moved_out": dict(getattr(db, "moved_out", {})),
        "split_partial": sorted(getattr(db, "split_partial", ())),
    }


def restore_state(payload: dict, db=None):
    """State payload -> GraphDB (fresh one by default). Refuses
    payloads stamped NEWER than this build understands (typed
    UnsupportedFormat); unstamped legacy payloads are version 0 and
    restore identically."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.storage.versions import check_format

    check_format(payload.get("format_version", 0), "snapshot payload")
    db = db or GraphDB()
    db.alter(payload["schema"])
    for pred, st in payload["tablets"].items():
        ps = db.schema.get_or_default(pred)
        tab = restore_tablet(pred, ps, st)
        db.tablets[pred] = tab
        db.coordinator.should_serve(pred)
        # CDC floor: history at or below the restored base lives in
        # the base state, not the change log — a subscriber resuming
        # from an older offset must get OffsetTruncated (re-sync via
        # snapshot read + resubscribe), never a silent gap
        db.cdc.reset_floor(pred, tab.max_commit_ts)
    db.coordinator.observe_ts(payload["max_ts"])
    db.coordinator.bump_uids(payload["next_uid"] - 1)
    db.pending_txns = {int(ts): (list(ops), list(keys))
                       for ts, (ops, keys)
                       in payload.get("pending_txns", {}).items()}
    db.moved_out = {p: int(g) for p, g
                    in payload.get("moved_out", {}).items()}
    db.split_partial = set(payload.get("split_partial", ()))
    return db


def save_snapshot(db, path: str):
    """Write the rolled-up store to one file. The gzip member pins
    mtime=0 so identical state produces identical FILE BYTES — the
    determinism contract distributed ingest's retried reduce shards
    are checked against (ingest/distributed.py)."""
    payload = dump_state(db)
    tmp = path + ".tmp"
    from dgraph_tpu import wire
    # compresslevel=6: level 9 costs ~7x the CPU of 6 for ~1% smaller
    # output on wire-encoded tablet payloads — at bulk-ingest scale
    # the snapshot encode IS the reduce tail, so the default-9 write
    # was the single largest line item of a shard's wall clock
    with open(tmp, "wb") as raw, \
            gzip.GzipFile(filename="", fileobj=raw, mode="wb",
                          mtime=0, compresslevel=6) as f:
        f.write(SNAPSHOT_MAGIC)
        f.write(wire.dumps(payload))
    os.replace(tmp, path)


def load_snapshot(path: str, db=None):
    """Restore a GraphDB from a snapshot file (fresh one by default)."""
    with gzip.open(path, "rb") as f:
        magic = f.read(len(SNAPSHOT_MAGIC))
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path!r} is not a dgraph-tpu snapshot")
        payload = _load_payload(f.read())
    return restore_state(payload, db)
