"""Store snapshots: serialize a GraphDB's rolled-up state to one file.

The analogue of the reference bulk loader's output (a ready Badger p/
directory, bulk/reduce.go writing SSTs) and the base artifact for
backup/restore (ee/backup/). Format: a pickle of schema text + per-
tablet base arrays + coordinator counters, gzip-compressed. Backups
(backup.py) layer manifest chains and incremental deltas on top.
"""

from __future__ import annotations

import gzip
import os
import pickle

SNAPSHOT_MAGIC = b"DGTPU-SNAP-1"


def save_snapshot(db, path: str):
    """Write the rolled-up store. Pending deltas are folded first so the
    snapshot is a pure base state at a single ts."""
    db.rollup_all()
    tablets = {}
    for pred, tab in db.tablets.items():
        tablets[pred] = {
            "edges": tab.edges,
            "reverse": tab.reverse,
            "values": tab.values,
            "index": tab.index,
            "edge_facets": tab.edge_facets,
            "base_ts": tab.base_ts,
        }
    payload = {
        "schema": db.schema.describe_all(),
        "tablets": tablets,
        "max_ts": db.coordinator.max_assigned(),
        "next_uid": db.coordinator._next_uid,
    }
    tmp = path + ".tmp"
    with gzip.open(tmp, "wb") as f:
        f.write(SNAPSHOT_MAGIC)
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load_snapshot(path: str, db=None):
    """Restore a GraphDB from a snapshot file (fresh one by default)."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.storage.tablet import Tablet

    with gzip.open(path, "rb") as f:
        magic = f.read(len(SNAPSHOT_MAGIC))
        if magic != SNAPSHOT_MAGIC:
            raise ValueError(f"{path!r} is not a dgraph-tpu snapshot")
        payload = pickle.load(f)
    db = db or GraphDB()
    db.alter(payload["schema"])
    for pred, st in payload["tablets"].items():
        ps = db.schema.get_or_default(pred)
        tab = Tablet(pred, ps)
        tab.edges = st["edges"]
        tab.reverse = st["reverse"]
        tab.values = st["values"]
        tab.index = st["index"]
        tab.edge_facets = st["edge_facets"]
        tab.base_ts = st["base_ts"]
        db.tablets[pred] = tab
        db.coordinator.should_serve(pred)
    while db.coordinator.max_assigned() < payload["max_ts"]:
        db.coordinator.next_ts()
    db.coordinator.bump_uids(payload["next_uid"] - 1)
    return db
