"""At-rest and on-wire version contracts for rolling upgrades.

The reference negotiates through protobuf's open-ended field numbering
plus explicit manifest versions (ee/backup/ Manifest.Version,
x/x.go DgraphVersion checks at restore). We make both contracts
explicit and testable:

  FORMAT_VERSION    stamped into snapshot payloads and backup
                    manifests/headers. Files written before the stamp
                    existed carry NO version key and load as version 0
                    — the pinned legacy contract
                    (tests/test_format_version.py drives committed
                    legacy bytes through restore). A reader refuses
                    formats NEWER than it understands with the typed
                    UnsupportedFormat instead of misparsing.

  PROTOCOL_VERSION  advertised by the `hello` wire op on alphas and
                    zeros; a connecting peer negotiates
                    min(ours, theirs) (negotiate()). Today every
                    protocol change has been additive (new dict keys,
                    new record tags), so min() is always servable —
                    the negotiation surface exists so the FIRST
                    breaking change has somewhere to land, and so a
                    rolling upgrade can assert the fleet's spread
                    (tools/dgchaos.py rolling-upgrade nemesis).

  build version     a free-form string (DGRAPH_TPU_BUILD_VERSION env,
                    default "dev") surfaced on /debug/stats and hello.
                    The rolling-upgrade drill restarts nodes with a
                    new build string one at a time and asserts mixed
                    fleets interoperate checker-green.
"""

from __future__ import annotations

import os

# at-rest payload format (snapshots, backups). 0 = pre-stamp legacy.
FORMAT_VERSION = 1
# cluster wire protocol (the request/response op surface)
PROTOCOL_VERSION = 1


class UnsupportedFormat(ValueError):
    """The artifact was written by a NEWER format than this node
    understands — restoring it could silently misparse. Upgrade the
    node (or restore with a build >= the writer's)."""

    def __init__(self, what: str, version: int):
        self.what = what
        self.version = version
        super().__init__(
            f"{what} has format_version {version}, newer than this "
            f"build's {FORMAT_VERSION}; upgrade before restoring")


def check_format(version: int, what: str) -> int:
    """Gate an at-rest artifact's stamped version (absent = 0 legacy,
    always accepted). Returns the version for the caller to log."""
    v = int(version)
    if v > FORMAT_VERSION:
        raise UnsupportedFormat(what, v)
    return v


def negotiate(peer_protocol: int) -> int:
    """Both sides speak min(ours, theirs) — the protobuf discipline
    (old readers skip unknown additive fields) made explicit."""
    return min(PROTOCOL_VERSION, int(peer_protocol))


def build_version() -> str:
    return os.environ.get("DGRAPH_TPU_BUILD_VERSION", "dev")


def versions_payload() -> dict:
    """The `hello` wire-op / debug-stats versions block."""
    return {"protocol": PROTOCOL_VERSION, "format": FORMAT_VERSION,
            "build": build_version()}
