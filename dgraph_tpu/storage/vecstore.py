"""Per-predicate columnar vector store.

The vector analogue of Tablet.value_columns: a float32vector
predicate's embeddings packed into one dense (n, d) float32 block
aligned to a sorted uid row map, built from the tablet's BASE state and
cached per (base_ts, schema) — exactly the contract the device tiles
and columnar views follow (storage/tablet.py value_columns,
engine/device_cache.py).

MVCC overlay semantics match the posting-list reads: the base block
answers every row the overlay does NOT touch at read_ts; overlay-
touched uids (Tablet.overlay_srcs) are masked out of the base block and
re-read through the exact MVCC path (get_postings at read_ts) into a
small side block. ops/knn.py scores base and overlay rows and merges
their top-k, so a mutation is visible at its commit_ts and invisible
below it without ever rebuilding the big block.

Ref: modern Dgraph's vector index attaches to the posting list the same
way (posting/index.go vector index entries); here the "index" IS the
brute-force block, per TPU-KNN (PAPERS.md 2206.14286) — at peak matmul
throughput brute-force beats pointer-chasing structures on this
hardware.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dgraph_tpu.models.types import TypeID, vector_value

_EMPTY_U64 = np.empty(0, dtype=np.uint64)


@dataclass
class VecView:
    """One read-timestamp's view of a vector tablet.

    base_uids/base_vecs are the packed BASE block (stable per base_ts —
    safe to keep device-resident); base_keep masks off rows the overlay
    touches at this read_ts. extra_uids/extra_vecs are the overlay-
    visible rows, read through MVCC at read_ts.
    """

    dim: int
    base_uids: np.ndarray       # [n] uint64 sorted
    base_vecs: np.ndarray       # [n, d] float32, C-contiguous
    base_keep: np.ndarray       # [n] bool
    extra_uids: np.ndarray      # [m] uint64 sorted
    extra_vecs: np.ndarray      # [m, d] float32

    @property
    def n_rows(self) -> int:
        return int(self.base_keep.sum()) + len(self.extra_uids)


def _posting_vec(tab, ps) -> np.ndarray | None:
    """First untagged posting's embedding, or None."""
    for p in ps:
        if p.lang:
            continue
        v = p.value
        if v.tid != TypeID.FLOAT32VECTOR:
            v = None
            try:
                from dgraph_tpu.models.types import convert
                v = convert(p.value, TypeID.FLOAT32VECTOR)
            except ValueError:
                return None
        return np.asarray(vector_value(v), np.float32)
    return None


def _base_block(tab) -> tuple[np.ndarray, np.ndarray]:
    """Packed (uids, (n, d) float32) of the tablet's base state, cached
    per (base_ts, schema object) like value_columns. Raises ValueError
    on mixed dimensions — a brute-force block has no meaningful score
    between differently-sized embeddings."""
    cached = getattr(tab, "_vec_base", None)
    if cached is not None and cached[0] == tab.base_ts \
            and cached[1] is tab.schema:
        return cached[2], cached[3]
    uids: list[int] = []
    rows: list[np.ndarray] = []
    dim = None
    for u, ps in tab.values.items():
        vec = _posting_vec(tab, ps)
        if vec is None:
            continue
        if dim is None:
            dim = len(vec)
        elif len(vec) != dim:
            raise ValueError(
                f"predicate {tab.pred!r} holds vectors of differing "
                f"dimension ({dim} vs {len(vec)})")
        uids.append(u)
        rows.append(vec)
    if dim is None:
        uarr = _EMPTY_U64.copy()
        varr = np.empty((0, 0), np.float32)
    else:
        uarr = np.asarray(uids, np.uint64)
        order = np.argsort(uarr, kind="stable")
        uarr = uarr[order]
        varr = np.ascontiguousarray(
            np.stack(rows, axis=0)[order], dtype=np.float32)
    tab._vec_base = (tab.base_ts, tab.schema, uarr, varr)
    return uarr, varr


def vector_view(tab, read_ts: int) -> VecView:
    """The tablet's vectors visible at read_ts. The base block is
    shared across calls; only the (usually tiny) overlay side block is
    built per read timestamp."""
    base_uids, base_vecs = _base_block(tab)
    dim = base_vecs.shape[1] if base_vecs.size else 0
    keep = np.ones(len(base_uids), bool)
    ex_uids: list[int] = []
    ex_rows: list[np.ndarray] = []
    if tab.dirty():
        touched = sorted(tab.overlay_srcs(read_ts))
        if touched:
            tarr = np.asarray(touched, np.uint64)
            pos = np.searchsorted(base_uids, tarr)
            pos = np.clip(pos, 0, max(len(base_uids) - 1, 0))
            hit = (base_uids[pos] == tarr) if len(base_uids) \
                else np.zeros(len(tarr), bool)
            keep[pos[hit]] = False
            for u in touched:
                vec = _posting_vec(tab, tab.get_postings(int(u), read_ts))
                if vec is None:
                    continue
                if dim == 0:
                    dim = len(vec)
                elif len(vec) != dim:
                    raise ValueError(
                        f"predicate {tab.pred!r} holds vectors of "
                        f"differing dimension ({dim} vs {len(vec)})")
                ex_uids.append(int(u))
                ex_rows.append(vec)
    if ex_uids:
        earr = np.asarray(ex_uids, np.uint64)
        order = np.argsort(earr, kind="stable")
        ex_u = earr[order]
        ex_v = np.ascontiguousarray(
            np.stack(ex_rows, axis=0)[order], dtype=np.float32)
    else:
        ex_u = _EMPTY_U64.copy()
        ex_v = np.empty((0, dim), np.float32)
    if not base_vecs.size and dim:
        base_vecs = np.empty((0, dim), np.float32)
    return VecView(dim, base_uids, base_vecs, keep, ex_u, ex_v)
