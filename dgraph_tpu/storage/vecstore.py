"""Per-predicate columnar vector store.

The vector analogue of Tablet.value_columns: a float32vector
predicate's embeddings packed into one dense (n, d) float32 block
aligned to a sorted uid row map, built from the tablet's BASE state and
cached per (base_ts, schema) — exactly the contract the device tiles
and columnar views follow (storage/tablet.py value_columns,
engine/device_cache.py).

MVCC overlay semantics match the posting-list reads: the base block
answers every row the overlay does NOT touch at read_ts; overlay-
touched uids (Tablet.overlay_srcs) are masked out of the base block and
re-read through the exact MVCC path (get_postings at read_ts) into a
small side block. ops/knn.py scores base and overlay rows and merges
their top-k, so a mutation is visible at its commit_ts and invisible
below it without ever rebuilding the big block.

Ref: modern Dgraph's vector index attaches to the posting list the same
way (posting/index.go vector index entries); here the "index" IS the
brute-force block, per TPU-KNN (PAPERS.md 2206.14286) — at peak matmul
throughput brute-force beats pointer-chasing structures on this
hardware.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dgraph_tpu.models.types import TypeID, vector_value
from dgraph_tpu.utils import failpoint
from dgraph_tpu.utils.metrics import set_gauge

_EMPTY_U64 = np.empty(0, dtype=np.uint64)


@dataclass
class VecView:
    """One read-timestamp's view of a vector tablet.

    base_uids/base_vecs are the packed BASE block (stable per base_ts —
    safe to keep device-resident); base_keep masks off rows the overlay
    touches at this read_ts. extra_uids/extra_vecs are the overlay-
    visible rows, read through MVCC at read_ts.
    """

    dim: int
    base_uids: np.ndarray       # [n] uint64 sorted
    base_vecs: np.ndarray       # [n, d] float32, C-contiguous
    base_keep: np.ndarray       # [n] bool
    extra_uids: np.ndarray      # [m] uint64 sorted
    extra_vecs: np.ndarray      # [m, d] float32

    @property
    def n_rows(self) -> int:
        return int(self.base_keep.sum()) + len(self.extra_uids)


def _posting_vec(tab, ps) -> np.ndarray | None:
    """First untagged posting's embedding, or None."""
    for p in ps:
        if p.lang:
            continue
        v = p.value
        if v.tid != TypeID.FLOAT32VECTOR:
            v = None
            try:
                from dgraph_tpu.models.types import convert
                v = convert(p.value, TypeID.FLOAT32VECTOR)
            except ValueError:
                return None
        return np.asarray(vector_value(v), np.float32)
    return None


def _base_block(tab) -> tuple[np.ndarray, np.ndarray]:
    """Packed (uids, (n, d) float32) of the tablet's base state, cached
    per (base_ts, schema object) like value_columns. Raises ValueError
    on mixed dimensions — a brute-force block has no meaningful score
    between differently-sized embeddings."""
    cached = getattr(tab, "_vec_base", None)
    if cached is not None and cached[0] == tab.base_ts \
            and cached[1] is tab.schema:
        return cached[2], cached[3]
    uids: list[int] = []
    rows: list[np.ndarray] = []
    dim = None
    for u, ps in tab.values.items():
        vec = _posting_vec(tab, ps)
        if vec is None:
            continue
        if dim is None:
            dim = len(vec)
        elif len(vec) != dim:
            raise ValueError(
                f"predicate {tab.pred!r} holds vectors of differing "
                f"dimension ({dim} vs {len(vec)})")
        uids.append(u)
        rows.append(vec)
    if dim is None:
        uarr = _EMPTY_U64.copy()
        varr = np.empty((0, 0), np.float32)
    else:
        uarr = np.asarray(uids, np.uint64)
        order = np.argsort(uarr, kind="stable")
        uarr = uarr[order]
        varr = np.ascontiguousarray(
            np.stack(rows, axis=0)[order], dtype=np.float32)
    tab._vec_base = (tab.base_ts, tab.schema, uarr, varr)
    return uarr, varr


def vector_view(tab, read_ts: int) -> VecView:
    """The tablet's vectors visible at read_ts. The base block is
    shared across calls; only the (usually tiny) overlay side block is
    built per read timestamp."""
    base_uids, base_vecs = _base_block(tab)
    dim = base_vecs.shape[1] if base_vecs.size else 0
    keep = np.ones(len(base_uids), bool)
    ex_uids: list[int] = []
    ex_rows: list[np.ndarray] = []
    if tab.dirty():
        touched = sorted(tab.overlay_srcs(read_ts))
        if touched:
            tarr = np.asarray(touched, np.uint64)
            pos = np.searchsorted(base_uids, tarr)
            pos = np.clip(pos, 0, max(len(base_uids) - 1, 0))
            hit = (base_uids[pos] == tarr) if len(base_uids) \
                else np.zeros(len(tarr), bool)
            keep[pos[hit]] = False
            for u in touched:
                vec = _posting_vec(tab, tab.get_postings(int(u), read_ts))
                if vec is None:
                    continue
                if dim == 0:
                    dim = len(vec)
                elif len(vec) != dim:
                    raise ValueError(
                        f"predicate {tab.pred!r} holds vectors of "
                        f"differing dimension ({dim} vs {len(vec)})")
                ex_uids.append(int(u))
                ex_rows.append(vec)
    if ex_uids:
        earr = np.asarray(ex_uids, np.uint64)
        order = np.argsort(earr, kind="stable")
        ex_u = earr[order]
        ex_v = np.ascontiguousarray(
            np.stack(ex_rows, axis=0)[order], dtype=np.float32)
    else:
        ex_u = _EMPTY_U64.copy()
        ex_v = np.empty((0, dim), np.float32)
    if not base_vecs.size and dim:
        base_vecs = np.empty((0, dim), np.float32)
    return VecView(dim, base_uids, base_vecs, keep, ex_u, ex_v)


# ---------------------------------------------------------------------------
# quantized IVF index (ops/ivf.py) — trained on clean base blocks,
# versioned per (base_ts, schema) exactly like the columnar exports
# ---------------------------------------------------------------------------


def vector_ivf(tab):
    """The tablet's trained quantized index, or None. Valid only for
    the CURRENT (base_ts, schema): a rollup that folds vector ops
    moves base_ts and the stale index silently disappears — overlay
    rows between rollups ride the exact path (vector_view), so
    snapshot semantics never depend on index freshness."""
    cached = getattr(tab, "_vec_ivf", None)
    if cached is not None and cached[0] == tab.base_ts \
            and cached[1] is tab.schema:
        return cached[2]
    return None


def build_ivf(tab, *, nlist=None, seed: int = 0,
              target_recall: float | None = None, min_rows: int = 0,
              force: bool = False):
    """Train (or reuse) the quantized index over the tablet's base
    block. Returns the index, or None when the block is empty /
    below min_rows. The build is deterministic per (block, seed):
    two replicas training over the same base state produce
    byte-identical codebooks — the property snapshot determinism
    (ingest/distributed.py) leans on."""
    from dgraph_tpu.ops import ivf as _ivf
    from dgraph_tpu.utils.tracing import span as _span

    cur = vector_ivf(tab)
    if cur is not None and not force:
        return cur
    _uids, vecs = _base_block(tab)
    n = len(vecs)
    if n == 0 or (not force and n < min_rows):
        return None
    failpoint.fire("vecstore.build")
    with _span("vector.build", pred=tab.pred, rows=n):
        kw = {}
        if target_recall is not None:
            kw["target_recall"] = float(target_recall)
        ix = _ivf.build(vecs, nlist=nlist, seed=seed, **kw)
    tab._vec_ivf = (tab.base_ts, tab.schema, ix)
    set_gauge("vector_index_bytes", float(ix.nbytes),
              labels={"predicate": tab.pred})
    return ix


def ivf_residency(tab) -> dict:
    """Vector-plane residency for tabstats: decoded base block bytes
    plus the quantized index's footprint (0 when stale/absent)."""
    out = {"vecBase": 0, "vecIndex": 0}
    vb = getattr(tab, "_vec_base", None)
    if vb is not None and vb[0] == tab.base_ts and vb[1] is tab.schema:
        out["vecBase"] = int(vb[3].nbytes + vb[2].nbytes)
    ix = vector_ivf(tab)
    if ix is not None:
        out["vecIndex"] = int(ix.nbytes)
    return out


def ivf_to_payload(ix) -> dict:
    """Index -> wire-shape dict for the snapshot plane. Arrays ship
    as raw little-endian bytes + shape so the payload is
    byte-deterministic (the group-varint planes' contract; float
    blocks don't delta-compress, they stay dense)."""
    return {
        "v": 1, "dim": ix.dim, "nlist": ix.nlist,
        "nprobe": ix.nprobe,
        "sample_recall": float(ix.sample_recall),
        "target_recall": float(ix.target_recall),
        "seed": int(ix.seed),
        "centroids": ix.centroids.tobytes(),
        "order": ix.order.tobytes(),
        "starts": ix.starts.tobytes(),
        "codes": ix.codes.tobytes(),
        "scales": ix.scales.tobytes(),
        "norms2": ix.norms2.tobytes(),
    }


def ivf_from_payload(st: dict):
    from dgraph_tpu.ops.ivf import IVFIndex
    d, nc = int(st["dim"]), int(st["nlist"])
    n = len(st["order"]) // 4
    return IVFIndex(
        dim=d, nlist=nc,
        centroids=np.frombuffer(st["centroids"], "<f4")
        .reshape(nc, d).copy(),
        order=np.frombuffer(st["order"], "<i4").copy(),
        starts=np.frombuffer(st["starts"], "<i8").copy(),
        codes=np.frombuffer(st["codes"], "i1").reshape(n, d).copy(),
        scales=np.frombuffer(st["scales"], "<f4").copy(),
        norms2=np.frombuffer(st["norms2"], "<f4").copy(),
        nprobe=int(st["nprobe"]),
        sample_recall=float(st["sample_recall"]),
        target_recall=float(st["target_recall"]),
        seed=int(st.get("seed", 0)))
