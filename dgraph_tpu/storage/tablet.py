"""Per-predicate MVCC tablet.

Equivalent of the reference's posting-list layer for one predicate
(posting/list.go List + posting/index.go index/reverse/count upkeep), with
the storage model inverted for TPU residency:

  reference: Badger key per (pred, uid), immutable pack + per-txn deltas,
             iterator merges layers at read time (posting/list.go:559)
  here:      one Tablet per pred = base state (numpy dicts, rolled up at
             base_ts) + commit-ts-stamped delta overlay; reads at read_ts
             overlay deltas in (base_ts, read_ts]; rollup folds the
             overlay forward and re-packs device tiles (ops/graph.py)

Indexes (token->uids), reverse edges and counts are maintained
transactionally inside the same commit apply, mirroring
posting.AddMutationWithIndex (posting/index.go:377): an overwrite of a
single-valued indexed predicate first emits deletes for the old value's
tokens.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from dgraph_tpu.models.schema import PredicateSchema
from dgraph_tpu.models.tokenizer import get_tokenizer, tokens_for
from dgraph_tpu.models.types import (
    TypeID, Val, convert, sort_key, value_fingerprint,
)
from dgraph_tpu.utils import failpoint
from dgraph_tpu.utils.keys import token_bytes

_EMPTY = np.empty(0, dtype=np.uint64)


class ValueColumns:
    # dglint: guarded-by=*:external (owned by a Tablet; shares its
    # externally-synchronized discipline)
    """Columnar view of a scalar tablet's untagged values (the JSON
    fast path's input). Iterable as (srcs, tid, data, enc) and exposes
    .nbytes so DeviceCacheLRU can budget/evict it like a device tile —
    string payload copies are NOT free host memory.

    For string tablets, `extra_srcs`/`extra_enc` carry every
    LANG-TAGGED payload (absent from the untagged column) so batch
    scans like match() cover the full posting surface without a
    per-uid host pass; extra_ok=False marks a tablet whose tagged
    values defied encoding — batch consumers must fall back."""

    host_resident = True  # tile LRU: host bytes, never HBM

    __slots__ = ("srcs", "tid", "data", "enc", "nbytes",
                 "extra_srcs", "extra_enc", "extra_ok", "_ascii",
                 "_codes", "dt_secs", "dt_objs", "_blob",
                 "_sort_safe", "_bytes", "_dec")

    def __init__(self, srcs, tid, data, enc,
                 extra_srcs=None, extra_enc=None, extra_ok=True):
        self.srcs = srcs
        self.tid = tid
        self.data = data
        self.enc = enc
        self._codes = None
        self._blob = None
        self._sort_safe = None
        self._bytes = None
        self._dec = None
        # DATETIME tablets also carry the numeric column (float epoch
        # seconds, the dict math path's float() domain) plus the exact
        # datetime objects for var materialization
        self.dt_secs = None
        self.dt_objs = None
        self.extra_srcs = extra_srcs if extra_srcs is not None \
            else np.empty(0, np.uint64)
        self.extra_enc = extra_enc or []
        self.extra_ok = extra_ok
        self._ascii = None
        self.nbytes = int(srcs.nbytes) \
            + (int(data.nbytes) if data is not None else 0) \
            + (sum(len(e) + 49 for e in enc) if enc else 0) \
            + int(self.extra_srcs.nbytes) \
            + sum(len(e) + 49 for e in self.extra_enc)

    @property
    def ascii_only(self) -> bool:
        """Bytes-level regex over the payloads is only str-equivalent
        when every payload is ASCII ('.' must mean one codepoint).
        Computed lazily: only the regexp batch reads it, and the scan
        is O(total payload bytes)."""
        if self._ascii is None:
            self._ascii = all(e.isascii() for e in self.enc or []) \
                and all(e.isascii() for e in self.extra_enc)
        return self._ascii

    def __iter__(self):
        return iter((self.srcs, self.tid, self.data, self.enc))

    def payload_blob(self):
        """(uint8 blob, int64 offsets) of the payload column, joined
        ONCE per view lifetime — batch scanners (match, regexp) index
        into it instead of rebuilding python byte lists per query."""
        if self._blob is None:
            offs = np.zeros(len(self.enc or ()) + 1, np.int64)
            if self.enc:
                np.cumsum([len(e) for e in self.enc], out=offs[1:])
                blob = np.frombuffer(b"".join(self.enc), np.uint8)
            else:
                blob = np.zeros(1, np.uint8)
            self._blob = (blob, offs)
        return self._blob

    def enc_codes(self):
        """(codes int64 aligned to srcs, table: code -> bytes) for the
        string/datetime payload column — np fixed-width-bytes unique
        (C-order compare) instead of a per-row python dict pass, which
        was most of the 21M groupby-by-string profile. Cached for the
        colview's lifetime (per base_ts, like the view itself).
        Returns None when payloads carry trailing NULs ('S' dtype
        strips them, so codes would conflate distinct values)."""
        if self._codes is not None:
            return self._codes or None
        if not self.enc:
            self._codes = (np.empty(0, np.int64), [])
            return self._codes
        arr = np.asarray(self.enc, dtype=np.bytes_)
        uniq, codes = np.unique(arr, return_inverse=True)
        table = uniq.tolist()  # strips trailing NULs
        lens = np.fromiter((len(e) for e in self.enc),
                           np.int64, len(self.enc))
        tlens = np.asarray([len(t) for t in table], np.int64)
        if not np.array_equal(tlens[codes], lens):
            self._codes = False  # NUL-tailed payloads: exact path
            return None
        self._codes = (codes.astype(np.int64), table)
        return self._codes

    def decoded(self) -> list:
        """Payloads decoded back to str, ONCE per view lifetime — the
        emission paths gather from this instead of re-decoding the
        same bytes on every query (enc came from str.encode, so the
        round-trip cannot fail)."""
        if self._dec is None:
            self._dec = [e.decode("utf-8") for e in self.enc or ()]
        return self._dec

    # fixed-width byte matrices are rows x WIDEST payload: bound the
    # footprint so one multi-KB outlier payload can't inflate a
    # million-row column into gigabytes on the first string compare
    _BYTES_COL_CAP = 64 << 20

    def bytes_column(self):
        """(untagged 'S' array aligned to srcs, extra 'S' array aligned
        to extra_srcs) for vectorized string compares: UTF-8 byte order
        equals codepoint order, so fixed-width byte comparisons ARE the
        host loop's str comparisons. None when any payload embeds a NUL
        byte — the 'S' dtype strips trailing NULs, which would conflate
        distinct values — or when the rows x max-width matrix would
        exceed the footprint cap. Cached for the view's lifetime."""
        if self._bytes is not None:
            return self._bytes or None
        wid = max((len(e) for e in self.enc or ()), default=1)
        ewid = max((len(e) for e in self.extra_enc), default=1)
        if len(self.enc or ()) * wid > self._BYTES_COL_CAP \
                or len(self.extra_enc) * ewid > self._BYTES_COL_CAP:
            self._bytes = False
            return None
        if any(b"\x00" in e for e in self.enc or ()) \
                or any(b"\x00" in e for e in self.extra_enc):
            self._bytes = False
            return None
        main = np.asarray(self.enc, np.bytes_) if self.enc \
            else np.empty(0, "S1")
        extra = np.asarray(self.extra_enc, np.bytes_) \
            if self.extra_enc else np.empty(0, "S1")
        self._bytes = (main, extra)
        return self._bytes

    def enc_sort_safe(self) -> bool:
        """True when sorting the DECODED payload strings by
        str((v,)) — the groupby output-ordering contract — equals
        sorting the raw bytes: every byte printable ASCII with no
        quote/backslash, so repr() wraps each value identically and
        UTF-8 byte order is codepoint order. Cached per view."""
        if self._sort_safe is None:
            if not self.enc:
                self._sort_safe = True
            else:
                # bytes must be STRICTLY above the closing quote 0x27
                # that str((v,)) appends: with any byte below it, a
                # value that extends a shorter prefix ("New York" vs
                # "New") sorts after the prefix in byte order but
                # BEFORE it in the quoted contract order
                b = np.frombuffer(b"".join(self.enc), np.uint8)
                self._sort_safe = bool(
                    ((b > 0x27) & (b < 127) & (b != 0x5C)).all())
        return self._sort_safe


class TokenIndexCSR:
    """CSR export of a clean tablet's token index: every posting list
    concatenated into ONE sorted-run uid buffer with per-token offsets,
    so a k-token probe is k dict hits + k contiguous slices feeding one
    k-way merge (ops/setops) — no per-token overlay generators, no
    k-1 incremental union re-sorts.  The reference's UidPack blocks
    play the same role for its posting iterator (codec/codec.go:43).

    Exposes .nbytes so DeviceCacheLRU budgets it like a device tile."""

    host_resident = True

    __slots__ = ("rows", "offsets", "uids", "nbytes",
                 "posting_nbytes")

    def __init__(self, index: dict[bytes, np.ndarray]):
        toks = list(index.keys())
        self.rows = {t: i for i, t in enumerate(toks)}
        self.offsets = np.zeros(len(toks) + 1, np.int64)
        if toks:
            np.cumsum([len(index[t]) for t in toks],
                      out=self.offsets[1:])
            self.uids = np.concatenate(
                [np.asarray(index[t], np.uint64) for t in toks]) \
                if int(self.offsets[-1]) else _EMPTY.copy()
        else:
            self.uids = _EMPTY.copy()
        # posting bytes (the uid plane) apart from the token-key map,
        # which every index export carries identically — the
        # compressed-vs-dense comparison the bench gates on
        self.posting_nbytes = int(self.uids.nbytes) \
            + int(self.offsets.nbytes)
        self.nbytes = self.posting_nbytes \
            + sum(len(t) + 49 for t in toks)

    def probe(self, token: bytes) -> np.ndarray:
        """The token's sorted posting slice (empty when absent)."""
        i = self.rows.get(token)
        if i is None:
            return _EMPTY
        return self.uids[int(self.offsets[i]): int(self.offsets[i + 1])]


class CompressedTokenIndex:
    """Hybrid compressed export of a clean tablet's token index —
    sized by WHERE the bytes are, not by token count: real token
    indexes are zipfian (at the bench regime ~74% of tokens are
    singletons while ~80% of the uids live in the few hundred long
    posting lists), so

      * posting lists >= PACK_MIN uids become
        ops/codec.CompressedPack operands (adaptive array / bitmap /
        run blocks, ~2 B/uid and far below on runny lists) — set
        algebra runs on the compressed forms with block-descriptor
        skipping (ops/setops pack + mixed kernels);
      * the long tail of tiny lists stays one shared dense CSR
        buffer: per-token roaring descriptors would cost MORE than
        the 8 B/uid they replace, and a zero-copy slice keeps the
        many-token probes (trigram OR-trees, geo cell covers) at
        dense-tier speed.

    The tile LRU budgets this object by the resulting (mostly
    compressed) byte size.  The reference keeps the same split:
    group-varint UidPacks at rest (codec/codec.go), algo/uidlist.go
    intersecting block by block."""

    host_resident = True

    # below this posting-list length the roaring descriptor overhead
    # exceeds the dense bytes it saves; measured crossover on the
    # bench index shapes (bench_micro --setops-compressed)
    PACK_MIN = 128

    __slots__ = ("packs", "rows", "offsets", "uids", "nbytes",
                 "posting_nbytes")

    def __init__(self, index: dict[bytes, np.ndarray]):
        from dgraph_tpu.ops import codec as _codec
        self.packs = {}
        small: dict[bytes, np.ndarray] = {}
        for t, uids in index.items():
            if len(uids) >= self.PACK_MIN:
                self.packs[t] = _codec.compress(uids)
            else:
                small[t] = uids
        toks = list(small.keys())
        self.rows = {t: i for i, t in enumerate(toks)}
        self.offsets = np.zeros(len(toks) + 1, np.int64)
        if toks:
            np.cumsum([len(small[t]) for t in toks],
                      out=self.offsets[1:])
            self.uids = np.concatenate(
                [np.asarray(small[t], np.uint64) for t in toks]) \
                if int(self.offsets[-1]) else _EMPTY.copy()
        else:
            self.uids = _EMPTY.copy()
        self.posting_nbytes = \
            sum(p.nbytes for p in self.packs.values()) \
            + int(self.uids.nbytes) + int(self.offsets.nbytes)
        self.nbytes = self.posting_nbytes \
            + sum(len(t) + 49 for t in index)

    def probe_operand(self, token: bytes):
        """The token's set-algebra operand: a CompressedPack for long
        lists, a zero-copy dense slice for the small-list tail, None
        when absent — ops/setops' mixed kernels take either form."""
        p = self.packs.get(token)
        if p is not None:
            return p
        i = self.rows.get(token)
        if i is None:
            return None
        return self.uids[int(self.offsets[i]): int(self.offsets[i + 1])]

    def probe(self, token: bytes) -> np.ndarray:
        """Densified posting list (small tokens: the shared-buffer
        slice; packed tokens: a fresh decode).  A sanctioned DG09
        decode site: consumers that can, should use probe_operand."""
        op = self.probe_operand(token)
        if op is None:
            return _EMPTY
        if isinstance(op, np.ndarray):
            return op
        return op.densify()


class OrderPermutation:
    """One cached (key, uid)-sorted view of a sort-key column:
    `uids` in emission order, `perm` the permutation back into
    sort_key_arrays. Exposes .nbytes for the tile LRU."""

    host_resident = True

    __slots__ = ("uids", "perm", "nbytes")

    def __init__(self, uids: np.ndarray, perm: np.ndarray):
        self.uids = uids
        self.perm = perm
        self.nbytes = int(uids.nbytes) + int(perm.nbytes)


@dataclass
class Posting:
    """One value posting. Ref pb.Posting (value side)."""

    value: Val
    lang: str = ""
    facets: dict = field(default_factory=dict)


@dataclass
class EdgeOp:
    """One committed operation inside a tablet. op: 'set' | 'del' |
    'del_all' (S P * wildcard)."""

    op: str
    src: int
    dst: int = 0                       # uid objects
    posting: Optional[Posting] = None  # value objects
    facets: dict = field(default_factory=dict)


def _ins(arr: np.ndarray, uid: int) -> np.ndarray:
    i = np.searchsorted(arr, uid)
    if i < len(arr) and arr[i] == uid:
        return arr
    return np.insert(arr, i, uid)


def _rm(arr: np.ndarray, uid: int) -> np.ndarray:
    i = np.searchsorted(arr, uid)
    if i < len(arr) and arr[i] == uid:
        return np.delete(arr, i)
    return arr


class Tablet:
    # dglint: guarded-by=*:external (tablets are engine data-plane
    # state: mutated only by the raft-apply/write path, read under
    # the server's rw read lock — synchronization lives a layer up,
    # see GraphDB; racecheck witnesses contract violations)
    def __init__(self, pred: str, schema: PredicateSchema):
        self.pred = pred
        self.schema = schema
        self.base_ts = 0
        # base state (committed, <= base_ts)
        self.edges: dict[int, np.ndarray] = {}        # src -> sorted dst u64
        self.reverse: dict[int, np.ndarray] = {}      # dst -> sorted src u64
        self.values: dict[int, list[Posting]] = {}    # src -> postings
        self.index: dict[bytes, np.ndarray] = {}      # token -> sorted uids
        self.edge_facets: dict[tuple[int, int], dict] = {}
        # delta overlay: ts-ascending op lists
        self.deltas: list[tuple[int, list[EdgeOp]]] = []
        self.max_commit_ts = 0
        # per-uid overlay index (lazily built, extended on apply,
        # dropped on rollup): without it every per-uid read scans the
        # WHOLE visible overlay — O(total ops) per get_postings call,
        # which dominated profiles on bulk-mutated, un-rolled stores
        self._ov_by_src: dict[int, list] | None = None
        self._ov_by_dst: dict[int, list] | None = None
        self._ov_della: list | None = None
        # device snapshot cache (built lazily; see engine/device_cache —
        # residency is budgeted by the engine's DeviceCacheLRU)
        self._device_adj = None
        self._device_values = None
        self._device_adj_ts = -1
        # query-path lookups since boot (executor._tablet bumps it):
        # the stats plane's "hottest tablets" signal. A plain int —
        # GIL-atomic enough for a statistic, never for correctness.
        self.touches = 0

    # -- schema helpers --
    @property
    def is_uid(self) -> bool:
        return self.schema.value_type == TypeID.UID

    def _converted(self, p: Posting) -> Val:
        want = self.schema.value_type
        if want in (TypeID.DEFAULT,):
            return p.value
        return convert(p.value, want)

    def _tokens(self, p: Posting) -> list[bytes]:
        out = []
        for tname in self.schema.tokenizers:
            spec = get_tokenizer(tname)
            for t in tokens_for(p.value, spec, p.lang):
                out.append(token_bytes(spec.ident, t))
        return out

    # -- commit application (engine's apply loop calls this) --

    def apply(self, commit_ts: int, ops: list[EdgeOp]):
        """Append a committed delta. Ops are expanded with the implicit
        index/reverse maintenance (old-value token deletes etc.) at apply
        time so the overlay is self-contained for reads.

        Commits MUST apply in ts order: overlay consumers early-break
        on the ts-sorted deltas, and single-value overwrite expansion
        (del old + set new) is computed against apply-time state.  The
        service layer guarantees the order by applying decided 2PC
        finalizes sorted by commit_ts (_apply_finalizes); a violation
        here must surface as a hard error, never a silent mis-ordered
        append (a stripped assert once let a racing finalize lose a
        committed bank credit)."""
        # chaos seam: an armed `tablet.apply` failpoint delays or
        # fails a commit delta landing (the reference's Jepsen runs
        # surface the same window by killing alphas mid-apply)
        failpoint.fire("tablet.apply")
        if self.deltas and commit_ts <= self.max_commit_ts:
            raise RuntimeError(
                f"out-of-order commit apply: ts {commit_ts} after "
                f"{self.max_commit_ts} on tablet {self.pred!r}")
        self.deltas.append((commit_ts, ops))
        self.max_commit_ts = max(self.max_commit_ts, commit_ts)
        if self._ov_by_src is not None:
            self._ov_extend(commit_ts, ops)

    # -- overlay index upkeep --

    def _ov_extend(self, ts: int, ops: list[EdgeOp]):
        for idx, op in enumerate(ops):
            entry = (ts, idx, op)
            self._ov_by_src.setdefault(op.src, []).append(entry)
            if op.op == "del_all":
                self._ov_della.append(entry)
            elif op.dst:
                self._ov_by_dst.setdefault(op.dst, []).append(entry)

    def _ov_index(self):
        if self._ov_by_src is None:
            self._ov_by_src = {}
            self._ov_by_dst = {}
            self._ov_della = []
            for ts, ops in self.deltas:
                self._ov_extend(ts, ops)

    def _ov_drop(self):
        self._ov_by_src = None
        self._ov_by_dst = None
        self._ov_della = None

    def _src_overlay(self, src: int, read_ts: int):
        """This src's overlay ops visible at read_ts, in commit order."""
        self._ov_index()
        for ts, _, op in self._ov_by_src.get(src, ()):
            if ts > read_ts:
                break
            yield op

    # -- reads (read_ts snapshot) --

    def _overlay(self, read_ts: int):
        for ts, ops in self.deltas:
            if ts > read_ts:
                break
            yield from ops

    def _overlay_ts(self, read_ts: int):
        for ts, ops in self.deltas:
            if ts > read_ts:
                break
            for i, op in enumerate(ops):
                yield ts, i, op

    def _postings_before(self, src: int, ts: int, idx: int) -> list[Posting]:
        """Value postings of `src` just before op position (ts, idx) —
        used by wildcard deletes to find the tokens they must drop,
        including postings set earlier in the SAME commit."""
        out = list(self.values.get(src, ()))
        for dts, i, op in self._overlay_ts(ts):
            if dts == ts and i >= idx:
                break
            if op.src != src:
                continue
            if op.op == "del_all":
                out = []
            elif op.op == "set":
                out = self._merge_posting(out, op.posting)
            elif op.op == "del" and op.posting is not None:
                fp = value_fingerprint(op.posting.value)
                out = [p for p in out
                       if not (p.lang == op.posting.lang
                               and value_fingerprint(p.value) == fp)]
        return out

    def _dsts_before(self, src: int, ts: int, idx: int) -> np.ndarray:
        """Destination uids of `src` just before op position (ts, idx)."""
        out = self.edges.get(src, _EMPTY)
        dirty = False
        for dts, i, op in self._overlay_ts(ts):
            if dts == ts and i >= idx:
                break
            if op.src != src:
                continue
            if not dirty:
                out = out.copy()
                dirty = True
            if op.op == "set":
                out = _ins(out, op.dst)
            elif op.op == "del":
                out = _rm(out, op.dst)
            elif op.op == "del_all":
                out = _EMPTY
        return out

    def get_dst_uids(self, src: int, read_ts: int) -> np.ndarray:
        out = self.edges.get(src, _EMPTY)
        dirty = False
        for op in self._src_overlay(src, read_ts):
            if not dirty:
                out = out.copy()
                dirty = True
            if op.op == "set":
                out = _ins(out, op.dst)
            elif op.op == "del":
                out = _rm(out, op.dst)
            elif op.op == "del_all":
                out = _EMPTY
        return out

    def get_reverse_uids(self, dst: int, read_ts: int) -> np.ndarray:
        out = self.reverse.get(dst, _EMPTY)
        self._ov_index()
        # merge this dst's set/del ops with every del_all, in commit
        # order — both lists are already (ts, idx)-sorted, so a linear
        # two-pointer merge beats re-sorting per frontier uid
        entries = self._ov_by_dst.get(dst, [])
        if self._ov_della:
            import heapq
            entries = heapq.merge(entries, self._ov_della,
                                  key=lambda e: (e[0], e[1]))
        for ts, i, op in entries:
            if ts > read_ts:
                break
            if op.op == "set" and op.dst == dst:
                out = _ins(out, op.src)
            elif op.op == "del" and op.dst == dst:
                out = _rm(out, op.src)
            elif op.op == "del_all":
                # wildcard covers edges added earlier in the overlay too:
                # reconstruct src's out-edges just before this delete
                if dst in self._dsts_before(op.src, ts, i):
                    out = _rm(out, op.src)
        return out

    def get_postings(self, src: int, read_ts: int) -> list[Posting]:
        out = list(self.values.get(src, ()))
        for op in self._src_overlay(src, read_ts):
            if op.op == "del_all":
                out = []
            elif op.op == "set":
                out = self._merge_posting(out, op.posting)
            elif op.op == "del":
                fp = value_fingerprint(op.posting.value) if op.posting else None
                out = [p for p in out
                       if not (p.lang == (op.posting.lang if op.posting else "")
                               and (fp is None
                                    or value_fingerprint(p.value) == fp))]
        return out

    def _merge_posting(self, cur: list[Posting], p: Posting) -> list[Posting]:
        if self.schema.list_:
            fp = value_fingerprint(p.value)
            rest = [q for q in cur if value_fingerprint(q.value) != fp]
            return rest + [p]
        # single-valued: one posting per lang (ref posting lang handling)
        rest = [q for q in cur if q.lang != p.lang]
        return rest + [p]

    def merge_base_value(self, src: int, p: Posting):
        """Bulk-load seam: merge `p` into the BASE value list for
        `src` with the same list/lang replacement semantics as the
        MVCC apply path. Only loaders building base state below the
        tablet's base_ts (ingest/bulk.py) may call this — it bypasses
        the overlay entirely (dglint DG03 guards the private helper)."""
        self.values[src] = self._merge_posting(
            self.values.get(src, []), p)

    def index_uids(self, token: bytes, read_ts: int) -> np.ndarray:
        out = self.index.get(token, _EMPTY)
        dirty = False
        for ts, i, op in self._overlay_ts(read_ts):
            toks: Iterable[bytes] = ()
            if op.op in ("set", "del") and op.posting is not None \
                    and self.schema.indexed:
                toks = self._tokens(op.posting)
            elif op.op == "del_all" and self.schema.indexed:
                # wildcard delete: drop src from every token of every
                # posting live just before this delete (incl. postings
                # added earlier in the overlay — even in the same commit)
                for p in self._postings_before(op.src, ts, i):
                    for tk in self._tokens(p):
                        if tk == token:
                            if not dirty:
                                out = out.copy(); dirty = True
                            out = _rm(out, op.src)
                continue
            if token in toks:
                if not dirty:
                    out = out.copy(); dirty = True
                if op.op == "set":
                    out = _ins(out, op.src)
                else:
                    out = _rm(out, op.src)
            # an overwrite (set on single-valued pred) removes the uid
            # from tokens of the *old* value: handled by explicit del ops
            # emitted at commit build time (engine mutation path).
        return out

    def get_postings_at_base(self, src: int) -> list[Posting]:
        return list(self.values.get(src, ()))

    def token_index_csr(self, read_ts: int):
        """CSR export of the token index for batched probes (clean
        tablets only — overlay-carrying reads keep the exact per-token
        index_uids path). Cached per (base_ts, schema object), like
        value_columns: alter() rebinds the schema and rebuild_index
        replaces the dict, so both invalidators are covered."""
        if self.dirty() or read_ts < self.base_ts \
                or not self.schema.indexed:
            return None
        if len(self.index) > (1 << 18):
            # mostly-exact-token indexes (one tiny posting list per
            # distinct value): the python-loop concat of a million
            # arrays costs seconds per rollup while contiguous slices
            # buy nothing over dict gets — keep the direct path
            return None
        cached = getattr(self, "_tok_csr", None)
        if cached is not None \
                and getattr(self, "_tok_csr_ts", -1) == self.base_ts \
                and getattr(self, "_tok_csr_schema", None) \
                is self.schema:
            return cached
        csr = TokenIndexCSR(self.index)
        self._tok_csr = csr
        self._tok_csr_ts = self.base_ts
        self._tok_csr_schema = self.schema
        return csr

    def token_index_packs(self, read_ts: int):
        """Compressed token-index export (CompressedTokenIndex) — the
        compressed tier's operand plane. Same contract as
        token_index_csr: clean tablets only, cached per (base_ts,
        schema object), the same 2^18-token cap (mostly-exact-token
        indexes gain nothing over dict gets), rebuilt after rollup or
        alter. Build cost is encode-at-export (rollup-path), like the
        dense CSR and the device tiles."""
        if self.dirty() or read_ts < self.base_ts \
                or not self.schema.indexed:
            return None
        if len(self.index) > (1 << 18):
            return None
        cached = getattr(self, "_tok_packs", None)
        if cached is not None \
                and getattr(self, "_tok_packs_ts", -1) == self.base_ts \
                and getattr(self, "_tok_packs_schema", None) \
                is self.schema:
            return cached
        packs = CompressedTokenIndex(self.index)
        self._tok_packs = packs
        self._tok_packs_ts = self.base_ts
        self._tok_packs_schema = self.schema
        return packs

    def src_uids(self, read_ts: int) -> np.ndarray:
        """All uids with >=1 posting — has() root. Ref
        worker/task.go:2075. Clean tablets answer from one sorted
        array cached per base_ts: dict keys are unique already, so the
        python-set pass the overlay path needs is pure overhead here
        (a 1M-row has() root rebuilt a 1M-entry set every query)."""
        if not self.deltas:
            cached = getattr(self, "_src_uids_cache", None)
            if cached is not None and cached[0] == self.base_ts:
                return cached[1]
            store = self.edges if self.is_uid else self.values
            out = np.fromiter(store.keys(), np.uint64, len(store))
            out.sort()
            self._src_uids_cache = (self.base_ts, out)
            return out
        base = set(self.edges) if self.is_uid else set(self.values)
        for op in self._overlay(read_ts):
            if op.op == "set":
                base.add(op.src)
            elif op.op == "del_all":
                base.discard(op.src)
            elif op.op == "del":
                pass  # conservative: cheap check below
        out = np.fromiter(base, dtype=np.uint64, count=len(base))
        out.sort()
        # exact: drop uids whose postings are now empty
        keep = [u for u in out.tolist()
                if (len(self.get_dst_uids(u, read_ts)) if self.is_uid
                    else len(self.get_postings(u, read_ts)))]
        return np.asarray(keep, dtype=np.uint64)

    def dst_uids(self, read_ts: int) -> np.ndarray:
        """All uids appearing as an edge destination — the reverse-side
        analogue of src_uids (root scans over `~pred`)."""
        if not self.deltas:
            cached = getattr(self, "_dst_uids_cache", None)
            if cached is not None and cached[0] == self.base_ts:
                return cached[1]
            out = np.fromiter(self.reverse.keys(), np.uint64,
                              len(self.reverse))
            out.sort()
            self._dst_uids_cache = (self.base_ts, out)
            return out
        base = set(self.reverse)
        for op in self._overlay(read_ts):
            if op.op == "set" and self.is_uid:
                base.add(op.dst)
        out = np.fromiter(base, dtype=np.uint64, count=len(base))
        out.sort()
        keep = [u for u in out.tolist()
                if len(self.get_reverse_uids(u, read_ts))]
        return np.asarray(keep, dtype=np.uint64)

    def expand_frontier(self, frontier: np.ndarray, read_ts: int,
                        reverse: bool = False) -> np.ndarray:
        """Union of destination uids over a frontier — the single host
        implementation of one BFS level (device analogue:
        ops/graph.expand). Both the executor and GraphDB.bfs use this."""
        getter = self.get_reverse_uids if reverse else self.get_dst_uids
        parts = [getter(int(u), read_ts) for u in frontier.tolist()]
        parts = [p for p in parts if len(p)]
        if not parts:
            return _EMPTY.copy()
        return np.unique(np.concatenate(parts))

    def edge_count(self, reverse: bool = False) -> int:
        """Total base edges (cached per base_ts): the executor's
        device/host cost model sizes expansions with it."""
        cached = getattr(self, "_edge_count_cache", None)
        if cached is not None and cached[0] == self.base_ts:
            fwd, rev = cached[1], cached[2]
        else:
            fwd = sum(len(v) for v in self.edges.values())
            rev = sum(len(v) for v in self.reverse.values())
            self._edge_count_cache = (self.base_ts, fwd, rev)
        return rev if reverse else fwd

    def count_of(self, src: int, read_ts: int,
                 reverse: bool = False) -> int:
        if reverse:
            return len(self.get_reverse_uids(src, read_ts))
        if self.is_uid:
            return len(self.get_dst_uids(src, read_ts))
        return len(self.get_postings(src, read_ts))

    def count_table(self) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized fan-out counts over the BASE state: (sorted src
        uint64 array, aligned int64 counts) — the count-index column
        the reference maintains per @count predicate (posting/index.go
        count keys), recomputed per base_ts instead of per mutation.
        Overlay-touched uids must be answered via count_of; callers
        partition with overlay_srcs()."""
        cached = getattr(self, "_count_table", None)
        if cached is not None and cached[0] == self.base_ts:
            return cached[1], cached[2]
        store = self.edges if self.is_uid else self.values
        srcs = np.fromiter(store.keys(), np.uint64, len(store))
        order = np.argsort(srcs)
        srcs = srcs[order]
        counts = np.fromiter((len(store[int(s)]) for s in srcs),
                             np.int64, len(srcs))
        self._count_table = (self.base_ts, srcs, counts)
        return srcs, counts

    def get_facets(self, src: int, dst: int, read_ts: int) -> dict:
        out = self.edge_facets.get((src, dst), {})
        for op in self._src_overlay(src, read_ts):
            if op.op == "set" and op.dst == dst and op.facets:
                out = op.facets
        return out

    def value_columns(self, read_ts: int):
        """Columnar view of a CLEAN single-valued scalar tablet for the
        JSON fast path (ref query/outputnode.go fastJsonNode feeding
        valToBytes): (srcs sorted u64, tid, data, enc) where data is a
        typed numpy array aligned to srcs for INT/FLOAT/BOOL and None
        for strings, and enc is the per-src utf-8-encoded payload list
        for STRING/DEFAULT/DATETIME. Rows without an untagged posting
        are simply absent from srcs. Returns None when the tablet is
        dirty at read_ts, historical (read_ts < base_ts), list-typed,
        value-type-mixed, or schema-converted — those keep the exact
        per-posting path. Cached per base_ts, like the device tiles."""
        if self.dirty() or read_ts < self.base_ts or self.schema.list_:
            return None
        # validity = same base AND the same schema OBJECT (held by
        # reference, so a recycled id() can never false-validate):
        # alter() rebinds tab.schema, and a type change must
        # invalidate the typed view
        cached = getattr(self, "_val_cols", None)
        if cached is not None \
                and getattr(self, "_val_cols_ts", -1) == self.base_ts \
                and getattr(self, "_val_cols_schema", None) \
                is self.schema:
            return cached or None
        cols = self._build_value_columns()
        self._val_cols = cols if cols is not None else False
        self._val_cols_ts = self.base_ts
        self._val_cols_schema = self.schema
        return cols

    def lang_value_columns(self, read_ts: int, lang: str):
        """Columnar view of ONE language's postings (first posting per
        uid tagged `lang`) — the lang-tagged groupby/gather analogue of
        value_columns. Same clean-tablet contract; cached per
        (base_ts, lang) under a per-lang attribute so each language's
        column copy is individually budgeted/evictable by the tile
        LRU (one shared key would account only the first language)."""
        if self.dirty() or read_ts < self.base_ts or self.schema.list_:
            return None
        attr = f"_val_cols_lang@{lang}"
        cached = getattr(self, attr, None)
        if cached is not None \
                and getattr(self, attr + "_ts", -1) == self.base_ts \
                and getattr(self, attr + "_schema", None) \
                is self.schema:
            return cached or None
        from dgraph_tpu.models.types import TypeID
        srcs: list[int] = []
        vals: list = []
        tid = None
        for u, ps in self.values.items():
            sel = None
            for p in ps:
                if p.lang == lang:
                    sel = p
                    break
            if sel is None:
                continue
            v = sel.value
            if tid is None:
                tid = v.tid
            elif v.tid is not tid:
                tid = False  # mixed types: exact path only
                break
            srcs.append(u)
            vals.append(v.value)
        out = None
        if tid in (TypeID.STRING, TypeID.DEFAULT):
            order = np.argsort(np.asarray(srcs, np.uint64))
            try:
                enc = [vals[j].encode("utf-8") for j in order.tolist()]
                out = ValueColumns(
                    np.asarray(srcs, np.uint64)[order], tid, None, enc)
            except (AttributeError, ValueError):
                out = None
        setattr(self, attr, out if out is not None else False)
        setattr(self, attr + "_ts", self.base_ts)
        setattr(self, attr + "_schema", self.schema)
        return out

    def edge_table(self, read_ts: int):
        """Flat (src-repeated, dst) uint64 arrays of a CLEAN uid
        tablet, src-sorted — one vectorized join key for groupby over
        uid predicates instead of a per-member edges[] walk. Cached
        per base_ts."""
        if self.dirty() or read_ts < self.base_ts or not self.is_uid:
            return None
        cached = getattr(self, "_edge_table", None)
        if cached is not None and self._edge_table_ts == self.base_ts:
            return cached
        parts_s, parts_d = [], []
        for u in sorted(self.edges):
            d = self.edges[u]
            if not len(d):
                continue
            parts_d.append(np.asarray(d, np.uint64))
            parts_s.append(np.full(len(d), u, np.uint64))
        if parts_s:
            table = (np.concatenate(parts_s), np.concatenate(parts_d))
        else:
            table = (np.empty(0, np.uint64), np.empty(0, np.uint64))
        self._edge_table = table
        self._edge_table_ts = self.base_ts
        return table

    def _build_value_columns(self):
        from dgraph_tpu.models.types import TypeID
        stype = self.schema.value_type
        srcs: list[int] = []
        vals: list = []
        tid = None
        for u, ps in self.values.items():
            sel = None
            for p in ps:
                if not p.lang:
                    sel = p
                    break
            if sel is None:
                continue
            v = sel.value
            if tid is None:
                tid = v.tid
            elif v.tid is not tid:
                return None  # mixed types: exact path only
            srcs.append(u)
            vals.append(v.value)
        if tid is None:
            return None
        if stype != TypeID.DEFAULT and tid != stype:
            # stored tid predates a schema change; reads convert per
            # cell, which the columnar view would skip
            return None
        order = np.argsort(np.asarray(srcs, np.uint64))
        srcs_a = np.asarray(srcs, np.uint64)[order]
        try:
            if tid == TypeID.INT:
                data = np.asarray(vals, np.int64)[order]
                return ValueColumns(srcs_a, tid, data, None)
            if tid == TypeID.FLOAT:
                data = np.asarray(vals, np.float64)[order]
                return ValueColumns(srcs_a, tid, data, None)
            if tid == TypeID.BOOL:
                data = np.asarray(
                    [1 if v else 0 for v in vals], np.uint8)[order]
                return ValueColumns(srcs_a, tid, data, None)
            if tid == TypeID.DATETIME:
                from dgraph_tpu.models.types import iso8601
                enc = [iso8601(vals[j]).encode("utf-8")
                       for j in order.tolist()]
                vc = ValueColumns(srcs_a, tid, None, enc)
                vc.dt_secs = np.asarray(
                    [vals[j].timestamp() for j in order.tolist()],
                    np.float64)
                objs = np.empty(len(order), object)
                for i, j in enumerate(order.tolist()):
                    objs[i] = vals[j]
                vc.dt_objs = objs
                return vc
            if tid in (TypeID.STRING, TypeID.DEFAULT):
                enc = [vals[j].encode("utf-8") for j in order.tolist()]
                ex_srcs, ex_enc, ex_ok = [], [], True
                for u, ps in self.values.items():
                    for p in ps:
                        if not p.lang:
                            continue
                        try:
                            ex_enc.append(
                                p.value.value.encode("utf-8"))
                            ex_srcs.append(u)
                        except (AttributeError, ValueError):
                            ex_ok = False
                return ValueColumns(
                    srcs_a, tid, None, enc,
                    extra_srcs=np.asarray(ex_srcs, np.uint64),
                    extra_enc=ex_enc, extra_ok=ex_ok)
        except (TypeError, ValueError, AttributeError, OverflowError):
            # ValueError covers UnicodeEncodeError: a lone-surrogate
            # payload keeps the exact dict path on BOTH emitters
            return None
        return None

    # -- rollup (ref posting/list.go:708 Rollup + worker/draft.go:407) --

    def dirty(self) -> bool:
        return bool(self.deltas)

    def approx_bytes(self) -> int:
        """Rough resident size — the tablet-space report zero's
        rebalancer weighs moves by (ref zero/tablet.go:180 tablet
        sizes from membership updates)."""
        n = 0
        for arr in self.edges.values():
            n += arr.nbytes
        for arr in self.reverse.values():
            n += arr.nbytes
        for arr in self.index.values():
            n += arr.nbytes
        for plist in self.values.values():
            for p in plist:
                v = p.value.value
                n += 16 + (len(v) if isinstance(v, (str, bytes)) else 8)
        n += 64 * sum(len(ops) for _, ops in self.deltas)
        return n

    def overlay_srcs(self, read_ts: int, reverse: bool = False
                     ) -> set[int]:
        """Uids whose out-edges (in-edges with reverse=True) are
        touched by overlay ops visible at read_ts — the exactness
        boundary for overlay-on-device reads: rows NOT in this set are
        identical in the base arrays, so a device tile built at
        base_ts answers them exactly; touched rows take the host MVCC
        path (ref posting/mvcc.go: immutable layer + mutable layer
        split, read through both)."""
        out: set[int] = set()
        for op in self._overlay(read_ts):
            if op.op == "del_all":
                # wildcard wipes src's row AND removes src from every
                # dst's reverse row — which dsts is row-dependent, so
                # conservatively all of src's base+overlay targets
                out.add(op.src)
                if reverse:
                    out.update(self.base_dsts_of(op.src))
            else:
                out.add(op.dst if reverse else op.src)
        return out

    def base_dsts_of(self, src: int) -> list[int]:
        arr = self.edges.get(src)
        return arr.tolist() if arr is not None else []

    def rollup(self, watermark: int):
        """Fold deltas with ts <= watermark into base state."""
        if not self.deltas:
            return  # nothing to fold — skip the (traced) fold path
        from dgraph_tpu.utils.tracing import span as _span

        with _span("tablet.rollup", pred=self.pred,
                   deltas=len(self.deltas)) as sp:
            keep: list[tuple[int, list[EdgeOp]]] = []
            folded = False
            for ts, ops in self.deltas:
                if ts > watermark:
                    keep.append((ts, ops))
                    continue
                folded = True
                for op in ops:
                    self._fold(op)
                self.base_ts = max(self.base_ts, ts)
            self.deltas = keep
            sp["folded"] = folded
            if folded:
                self._device_adj_ts = -1  # invalidate device snapshot
                self._ov_drop()           # overlay index keys shifted

    def _fold(self, op: EdgeOp):
        src = op.src
        if op.op == "del_all":
            if self.is_uid:
                for dst in self.edges.pop(src, _EMPTY):
                    self.reverse[int(dst)] = _rm(
                        self.reverse.get(int(dst), _EMPTY), src)
                    self.edge_facets.pop((src, int(dst)), None)
            else:
                for p in self.values.pop(src, []):
                    if self.schema.indexed:
                        for tk in self._tokens(p):
                            self.index[tk] = _rm(
                                self.index.get(tk, _EMPTY), src)
            return
        if self.is_uid:
            if op.op == "set":
                self.edges[src] = _ins(self.edges.get(src, _EMPTY), op.dst)
                if self.schema.reverse:
                    self.reverse[op.dst] = _ins(
                        self.reverse.get(op.dst, _EMPTY), src)
                if op.facets:
                    self.edge_facets[(src, op.dst)] = op.facets
            else:
                self.edges[src] = _rm(self.edges.get(src, _EMPTY), op.dst)
                if not len(self.edges[src]):
                    del self.edges[src]
                if self.schema.reverse:
                    self.reverse[op.dst] = _rm(
                        self.reverse.get(op.dst, _EMPTY), src)
                self.edge_facets.pop((src, op.dst), None)
            return
        # value posting
        if op.op == "set":
            self.values[src] = self._merge_posting(
                self.values.get(src, []), op.posting)
            if self.schema.indexed:
                for tk in self._tokens(op.posting):
                    self.index[tk] = _ins(self.index.get(tk, _EMPTY), src)
        else:
            before = self.values.get(src, [])
            after = [p for p in before
                     if not (p.lang == op.posting.lang
                             and value_fingerprint(p.value)
                             == value_fingerprint(op.posting.value))]
            self.values[src] = after
            if not after:
                del self.values[src]
            if self.schema.indexed:
                for tk in self._tokens(op.posting):
                    self.index[tk] = _rm(self.index.get(tk, _EMPTY), src)

    # -- index (re)build: Alter adding @index to live data
    #    (ref posting/index.go:496 rebuilder) --

    # tokenizer names dgt_tokenize_batch covers for ASCII payloads
    _NATIVE_TOKS = frozenset(("term", "exact", "trigram", "fulltext"))

    def rebuild_index(self):
        # batch build: collect per token, ONE sort+unique per posting
        # list at the end — per-element sorted np.insert is O(n^2) and
        # dominated bulk-load profiles
        self.index = {}
        if not self.schema.indexed:
            return
        # `ready` holds token lists that are already sorted-unique
        # (single clean native chunk) — the common case; one np.unique
        # per token across 600k exact/term tokens was half the native
        # path's wall clock otherwise
        ready: dict[bytes, np.ndarray] = {}
        acc: dict[bytes, list[np.ndarray]] = {}
        rest = self._index_batch_native(ready, acc)
        pyacc: dict[bytes, list[int]] = {}
        for src, p in rest:
            for tk in self._tokens(p):
                pyacc.setdefault(tk, []).append(src)
        for tk, srcs in pyacc.items():
            acc.setdefault(tk, []).append(np.asarray(srcs, np.uint64))
        for tk, parts in acc.items():
            prev = ready.pop(tk, None)
            if prev is not None:
                parts.append(prev)
            ready[tk] = np.unique(np.concatenate(parts)) \
                if len(parts) > 1 else np.unique(parts[0])
        self.index = ready

    def _index_batch_native(self, ready: dict, acc: dict) -> list:
        """Tokenize the ASCII string postings through the C++ batch
        tokenizer (native.cc dgt_tokenize_batch) — the reference maps
        at 75-80k RDF/s WITH index entries (bulk/mapper.go:272) where
        the per-value python tokenizer managed ~20k.  Returns the
        postings the native path cannot serve bit-identically
        (non-ASCII, non-string-typed, non-English fulltext tags,
        tokenizers outside the native set); ASCII folding equals the
        python NFKD+casefold chain, so handled postings produce the
        same tokens."""
        from dgraph_tpu import native
        from dgraph_tpu.models.stemmer import lang_base

        toks = set(self.schema.tokenizers or ())
        if not toks or not toks <= self._NATIVE_TOKS \
                or not native.available():
            return [(src, p) for src, plist in self.values.items()
                    for p in plist]
        mode = (native.TOK_TERM if "term" in toks else 0) \
            | (native.TOK_TRIGRAM if "trigram" in toks else 0) \
            | (native.TOK_FULLTEXT_EN if "fulltext" in toks else 0) \
            | (native.TOK_EXACT if "exact" in toks else 0)
        idents = tuple(get_tokenizer(n).ident
                       for n in ("term", "trigram", "fulltext", "exact"))
        need_en = "fulltext" in toks
        rest: list = []
        srcs: list[int] = []
        payloads: list[bytes] = []

        def flush():
            if not srcs:
                return
            payload = b"".join(payloads)
            offsets = np.zeros(len(payloads) + 1, np.uint64)
            np.cumsum([len(b) for b in payloads],
                      out=offsets[1:], dtype=np.uint64)
            got = native.tokenize_batch(
                np.frombuffer(payload, np.uint8), offsets, mode, idents)
            src_arr = np.asarray(srcs, np.uint64)
            if got is None:
                rest.extend(
                    (int(s), p) for s, p in zip(srcs, chunk_postings))
            else:
                # within a chunk the groups are ascending value-index;
                # with strictly increasing srcs the gathered uid lists
                # are therefore already sorted-unique -> `ready`
                clean = len(src_arr) < 2 \
                    or bool(np.all(src_arr[1:] > src_arr[:-1]))
                for tk, grp in zip(*got):
                    arr = src_arr[grp]
                    if clean and tk not in acc and tk not in ready:
                        ready[tk] = arr
                        continue
                    prev = ready.pop(tk, None)
                    if prev is not None:
                        acc.setdefault(tk, []).append(prev)
                    acc.setdefault(tk, []).append(arr)
            srcs.clear()
            payloads.clear()
            chunk_postings.clear()

        chunk_postings: list = []
        for src, plist in self.values.items():
            for p in plist:
                v = p.value
                s = v.value
                if v.tid not in (TypeID.STRING, TypeID.DEFAULT) \
                        or not isinstance(s, str) or not s.isascii() \
                        or (need_en and p.lang
                            and lang_base(p.lang) != "en"):
                    rest.append((src, p))
                    continue
                srcs.append(src)
                payloads.append(s.encode("ascii"))
                chunk_postings.append(p)
                if len(srcs) >= 131072:
                    flush()
        flush()
        return rest

    def rebuild_reverse(self):
        self.reverse = {}
        if not (self.is_uid and self.schema.reverse):
            return
        if self.edges:
            # one flat (dst, src) sort instead of per-edge inserts
            srcs = np.concatenate([
                np.full(len(d), s, np.uint64)
                for s, d in self.edges.items()])
            dsts = np.concatenate(
                [d.astype(np.uint64) for d in self.edges.values()])
            order = np.lexsort((srcs, dsts))
            srcs, dsts = srcs[order], dsts[order]
            uniq, starts = np.unique(dsts, return_index=True)
            bounds = np.append(starts, len(srcs))
            self.reverse = {
                int(u): np.unique(srcs[bounds[i]:bounds[i + 1]])
                for i, u in enumerate(uniq)}

    # -- columnar vector block (float32vector predicates) --

    def vector_view(self, read_ts: int):
        """Dense (n, d) float32 view of this predicate's embeddings at
        read_ts: packed base block (cached per base_ts, device-
        cacheable) + MVCC overlay side rows. See storage/vecstore.py;
        ops/knn.py consumes it for similar_to()."""
        from dgraph_tpu.storage.vecstore import vector_view
        return vector_view(self, read_ts)

    def vector_ivf(self):
        """The trained quantized ANN index for the CURRENT base state,
        or None (stale after a rollup that folded vector ops — the
        exact tiers keep serving until retrain)."""
        from dgraph_tpu.storage.vecstore import vector_ivf
        return vector_ivf(self)

    def build_vector_ivf(self, **kw):
        """Train (or reuse) the quantized index over the base block
        (storage/vecstore.build_ivf)."""
        from dgraph_tpu.storage.vecstore import build_ivf
        return build_ivf(self, **kw)

    # -- sortable keys for device values --

    def sort_key_arrays(self, lang: str = ""):
        """(uids u64, int64 keys) of sort_key_pairs as cached arrays —
        an inequality root at the 21M regime otherwise paid a fresh
        1M-entry dict build + fromiter on EVERY query (ref
        worker/tokens.go:113 walks an index that already exists; this
        is our equivalent persistent structure). Cached per (base_ts,
        schema object, lang) exactly like value_columns."""
        cached = getattr(self, "_sk_arrays", None)
        tag = (self.base_ts, self.schema, lang)
        if cached is not None and cached[0][0] == self.base_ts \
                and cached[0][1] is self.schema and cached[0][2] == lang:
            return cached[1], cached[2]
        pairs = self.sort_key_pairs(lang)
        uids = np.fromiter(pairs.keys(), np.uint64, len(pairs))
        keys = np.fromiter(pairs.values(), np.int64, len(pairs))
        # uid-ASCENDING is part of the contract: consumers gather by
        # np.searchsorted (the values dict iterates in insertion
        # order, which mutation-built tablets do NOT keep sorted)
        order = np.argsort(uids, kind="stable")
        uids, keys = uids[order], keys[order]
        self._sk_arrays = (tag, uids, keys)
        return uids, keys

    def sorted_by_key_uids(self, lang: str = "", desc: bool = False):
        """(OrderPermutation, cache attr) — uids ordered by
        (key, uid asc), asc or desc on the key, ties always
        uid-ascending (the executor's lexsort contract), plus the
        permutation into sort_key_arrays. A single-key order-by over a
        large candidate set then reduces to ONE membership gather
        through this cached permutation instead of a per-query lexsort
        (ref worker/sort.go walks the value-ordered index the same
        way); the permutation lets the caller probe in the SMALLER
        direction (candidates into the uid-sorted column) and re-order
        the hit mask. Cached per (base_ts, schema) under a per-
        (lang, desc) attribute so DeviceCacheLRU can budget and evict
        each entry (the attr is the caller's budget key)."""
        attr = f"_ordperm@{lang}@{'d' if desc else 'a'}"
        cached = getattr(self, attr, None)
        if cached is not None \
                and getattr(self, attr + "_ts", -1) == self.base_ts \
                and getattr(self, attr + "_schema", None) \
                is self.schema:
            return cached, attr
        uids, keys = self.sort_key_arrays(lang)
        # desc via bitwise-not: monotone-decreasing int64 map with no
        # INT64_MIN negation overflow
        order = np.lexsort((uids, ~keys if desc else keys))
        out = OrderPermutation(uids[order], order)
        setattr(self, attr, out)
        setattr(self, attr + "_ts", self.base_ts)
        setattr(self, attr + "_schema", self.schema)
        return out, attr

    def sort_key_pairs(self, lang: str = "") -> dict[int, int]:
        """uid -> int64 sort key for ORDERING in `lang`. Unlike
        filters/emission (strict tag match), sorting falls back:
        requested tag, else the untagged value, else the first posting
        (ref posting.List.ValueFor — query1_test.go
        TestToFastJSONOrderLang sorts alias@en over untagged
        aliases)."""
        out = {}
        for src, plist in self.values.items():
            sel = None
            for p in plist:
                if p.lang == lang:
                    sel = p
                    break
            if sel is None and lang:
                for p in plist:
                    if not p.lang:
                        sel = p
                        break
                if sel is None and plist:
                    sel = plist[0]
            if sel is None:
                continue
            try:
                out[src] = sort_key(self._converted(sel))
            except ValueError:
                pass
        return out
