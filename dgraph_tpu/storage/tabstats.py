"""Per-predicate tablet statistics: the planner-facing stats plane.

The reference exposes per-predicate tablet sizes through /state (zero
tablet reports, zero/tablet.go:180) and little else; a cost-based
planner needs more — cardinalities, fan-out shape, index selectivity,
bytes. This module computes that per tablet, the repo way: everything
derivable from BASE state is computed lazily and cached per
`(base_ts, schema object)` (the same invalidation contract as
value_columns / token_index_csr — a rollup moves base_ts, an alter
rebinds the schema, and the next read recomputes), while the cheap
always-on fields (dirty overlay op count, query-path touches) read
live. That is the "incremental on clean tablets, refreshed at rollup"
discipline: mutations only grow the delta overlay (reported exactly as
`dirtyOps`), and the expensive aggregates recompute once per fold,
never per query.

`tablet_stats(tab)` returns one JSON-ready dict:

  predicate/type/baseTs       identity
  nSrc/nDst/edges/reverseEdges/nPostings   cardinalities
  fanout                      log2 histogram of per-src posting-list
                              sizes (bucket b = sizes with bit_length
                              b), plus max/avg — the expansion-size
                              estimator
  tokenIndex                  tokens, avg/max posting length — the
                              eq/terms selectivity estimator
  valueTypes                  posting count per stored TypeID
  bytesAtRest                 approx resident bytes (base + overlay)
  bytesDecoded / residency    bytes of each materialized columnar /
                              device export currently cached on the
                              tablet (the tile LRU's view of it)
  dirtyOps                    overlay ops not yet folded (live)
  touches                     query-path tablet lookups since boot
                              (live; the "hottest tablets" signal)

Consumed by `/debug/stats`, the enriched `/state`, `EXPLAIN`'s row
estimators (query/explain.py) and tools/dgtop.py.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from dgraph_tpu.models.types import type_name

# fan-out histogram covers bit_length 0..20 (sizes up to ~1M); the
# last bucket absorbs everything larger
FANOUT_BUCKETS = 21


def _fanout_hist(counts: np.ndarray) -> dict:
    if not len(counts):
        return {"hist": [0] * FANOUT_BUCKETS, "max": 0, "avg": 0.0}
    bl = np.minimum(
        np.ceil(np.log2(np.maximum(counts, 1) + 1)).astype(np.int64),
        FANOUT_BUCKETS - 1)
    hist = np.bincount(bl, minlength=FANOUT_BUCKETS)
    return {"hist": hist.tolist()[:FANOUT_BUCKETS],
            "max": int(counts.max()),
            "avg": round(float(counts.mean()), 3)}


def _resident_nbytes(obj: Any) -> int:
    """Best-effort byte size of a cached export: honors an explicit
    .nbytes (TokenIndexCSR/OrderPermutation), else sums ndarray attrs."""
    nb = getattr(obj, "nbytes", None)
    if isinstance(nb, (int, np.integer)):
        return int(nb)
    total = 0
    names = getattr(obj, "__slots__", None)
    if names is None:
        names = list(getattr(obj, "__dict__", {}))
    for name in names:
        v = getattr(obj, name, None)
        if isinstance(v, np.ndarray):
            total += v.nbytes
        elif isinstance(v, (list, tuple)) and v \
                and isinstance(v[0], (bytes, bytearray)):
            total += sum(len(b) for b in v)
    return total


def residency(tab) -> dict:
    """Which columnar/device exports are materialized on this tablet
    right now, and their decoded byte sizes (0 = not resident). These
    are exactly the caches the tile LRU budgets; dgtop shows them as
    the tablet's decoded footprint."""
    out: dict[str, int] = {}

    def add(label: str, attr: str, ts_attr: Optional[str] = None):
        obj = getattr(tab, attr, None)
        if obj is None or obj is False:
            out[label] = 0
            return
        if ts_attr is not None \
                and getattr(tab, ts_attr, -1) != tab.base_ts:
            out[label] = 0
            return
        out[label] = _resident_nbytes(obj)

    add("valueColumns", "_val_cols", "_val_cols_ts")
    add("tokenCSR", "_tok_csr", "_tok_csr_ts")
    add("edgeTable", "_edge_table", "_edge_table_ts")
    add("deviceAdj", "_device_adj", "_device_adj_ts")
    # vector plane: packed base block + quantized IVF index bytes
    # (storage/vecstore.ivf_residency; 0 when stale or absent)
    from dgraph_tpu.storage.vecstore import ivf_residency
    out.update(ivf_residency(tab))
    # the compressed token-index export is NOT a decoded structure —
    # it lands in compressed_residency()/bytesCompressed, never in
    # bytesDecoded (the whole point is the at-rest/decoded split)
    dv = 0
    for attr in list(vars(tab)):
        # "_device_values" plus per-language "_device_values@<lang>"
        # tiles (device_cache.device_values); companions append "_ts"
        # (suffix check, same caveat as the ordperm loop below)
        if (attr == "_device_values"
                or attr.startswith("_device_values@")) \
                and not attr.endswith("_ts"):
            if getattr(tab, attr + "_ts", -1) == tab.base_ts:
                obj = getattr(tab, attr)
                if obj is not None:
                    dv += _resident_nbytes(obj)
    out["deviceValues"] = dv
    sk = getattr(tab, "_sk_arrays", None)
    out["sortKeys"] = (sk[1].nbytes + sk[2].nbytes) \
        if sk is not None and sk[0][0] == tab.base_ts else 0
    perms = 0
    for attr in list(vars(tab)):
        # base attrs end "@a"/"@d"; their companions append "_ts" /
        # "_schema" (suffix check: a lang tag may contain either)
        if attr.startswith("_ordperm@") and not attr.endswith("_ts") \
                and not attr.endswith("_schema"):
            if getattr(tab, attr + "_ts", -1) == tab.base_ts:
                perms += _resident_nbytes(getattr(tab, attr))
    out["orderPerms"] = perms
    return out


def compressed_residency(tab) -> dict:
    """Compressed-at-rest exports currently materialized (the
    compressed tier's operand plane): bytes of structures that hold
    COMPRESSED blocks, reported apart from residency() so
    bytesDecoded keeps meaning 'dense decoded bytes' — the
    bytesAtRest/bytesDecoded split the bench regime gates on."""
    out: dict[str, int] = {"tokenPacks": 0}
    obj = getattr(tab, "_tok_packs", None)
    if obj is not None \
            and getattr(tab, "_tok_packs_ts", -1) == tab.base_ts:
        out["tokenPacks"] = _resident_nbytes(obj)
    return out


def _base_stats(tab) -> dict:
    """The per-base_ts aggregate (cached by tablet_stats)."""
    is_uid = tab.is_uid
    if is_uid:
        _srcs, counts = tab.count_table()  # cached per base_ts itself
        n_postings = int(counts.sum()) if len(counts) else 0
        if tab.reverse:
            n_dst = len(tab.reverse)
        elif 0 < n_postings <= (1 << 22):
            n_dst = int(len(np.unique(np.concatenate(
                [v for v in tab.edges.values() if len(v)]))))
        else:
            # no reverse index and too many edges for an exact pass:
            # unknown (a stat endpoint must not allocate an E-sized
            # scratch buffer per rollup)
            n_dst = -1 if n_postings else 0
        vtypes = {"uid": n_postings}
    else:
        counts = np.fromiter((len(v) for v in tab.values.values()),
                             np.int64, len(tab.values))
        n_postings = int(counts.sum()) if len(counts) else 0
        n_dst = 0
        vtypes: dict[str, int] = {}
        for plist in tab.values.values():
            for p in plist:
                nm = type_name(p.value.tid)
                vtypes[nm] = vtypes.get(nm, 0) + 1
    idx_lens = np.fromiter((len(v) for v in tab.index.values()),
                           np.int64, len(tab.index)) \
        if tab.index else np.empty(0, np.int64)
    token_index = {
        "tokens": int(len(tab.index)),
        "avgPostings": round(float(idx_lens.mean()), 3)
        if len(idx_lens) else 0.0,
        "maxPostings": int(idx_lens.max()) if len(idx_lens) else 0,
        # log2 histogram of per-token posting-list lengths (bucket b =
        # lengths with bit_length b, same convention as fanout) — the
        # token-selectivity DISTRIBUTION, so per-token row estimates
        # (query/planner.py token_quantile) have a real basis instead
        # of the tablet-wide mean: a Zipfian index whose avg is 3 but
        # whose hot token holds 100k postings stops estimating every
        # probe at 3
        "hist": _fanout_hist(idx_lens)["hist"],
    }
    return {
        "predicate": tab.pred,
        "type": type_name(tab.schema.value_type),
        "baseTs": tab.base_ts,
        "nSrc": int(len(tab.edges) if is_uid else len(tab.values)),
        "nDst": int(n_dst),
        "edges": int(tab.edge_count()),
        "reverseEdges": int(tab.edge_count(reverse=True)),
        "nPostings": n_postings,
        "fanout": _fanout_hist(counts),
        "tokenIndex": token_index,
        "valueTypes": vtypes,
        "indexed": bool(tab.schema.indexed),
        "tokenizers": list(tab.schema.tokenizers or ()),
        "bytesAtRest": int(tab.approx_bytes()),
    }


def tablet_base_stats(tab) -> dict:
    """JUST the per-base_ts cached aggregate — the planner-hot subset
    (cardinalities, token histogram), without the live residency walk
    tablet_stats() pays per call. The adaptive planner consults this
    on query hot paths: steady-state cost is one tuple compare + dict
    return. Callers needing overlay slack add `dirty_ops(tab)`."""
    cached = getattr(tab, "_stats_cache", None)
    if cached is not None and cached[0] == tab.base_ts \
            and cached[1] is tab.schema:
        return cached[2]
    base = _base_stats(tab)
    tab._stats_cache = (tab.base_ts, tab.schema, base)
    return base


def dirty_ops(tab) -> int:
    """Un-folded overlay op count (live, cheap)."""
    return sum(len(ops) for _, ops in tab.deltas)


def tablet_stats(tab) -> dict:
    """Full stats dict for one tablet: the per-base_ts aggregate
    (cached on the tablet, same contract as its other exports) plus
    the live overlay/residency fields recomputed every call."""
    base = tablet_base_stats(tab)
    res = residency(tab)
    comp = compressed_residency(tab)
    out = dict(base)
    out["dirtyOps"] = dirty_ops(tab)
    out["touches"] = int(getattr(tab, "touches", 0))
    out["residency"] = res
    out["compressedResidency"] = comp
    out["bytesDecoded"] = int(sum(res.values()))
    out["bytesCompressed"] = int(sum(comp.values()))
    ivf = getattr(tab, "vector_ivf", None)
    if ivf is not None:
        ix = ivf()
        if ix is not None:
            # trained quantized ANN index: the budget EXPLAIN costs
            # against and dgtop's vector-tier view
            out["vectorIndex"] = ix.describe()
    return out


def tablet_summary(tab) -> dict:
    """The cheap always-on subset for /state: no O(postings) work
    beyond what edge_count/approx caches already paid."""
    return {
        "predicate": tab.pred,
        "edges": int(tab.edge_count()),
        "srcs": int(len(tab.edges) if tab.is_uid else len(tab.values)),
        "bytes": int(tab.approx_bytes()),
        "dirtyOps": sum(len(ops) for _, ops in tab.deltas),
        "touches": int(getattr(tab, "touches", 0)),
        "baseTs": tab.base_ts,
    }
