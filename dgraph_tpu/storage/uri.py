"""Backup URI handlers (ref ee/backup/handler.go:159 NewUriHandler).

The reference dispatches backup destinations on URI scheme: bare paths
and file:// go to fileHandler, s3:// and minio:// to s3Handler (a minio
client). This build speaks the S3 REST protocol directly over
http.client with AWS Signature V4 (no SDK dependency):

  s3://bucket/prefix            AWS endpoint (or $AWS_ENDPOINT)
  minio://host:port/bucket/pfx  explicit endpoint, http by default,
                                ?secure=true for TLS (ref s3_handler.go)

Credentials come from the environment like the reference:
AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY (unsigned anonymous requests
when unset, matching minio's public-bucket mode).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import os
from datetime import datetime, timezone
from typing import Optional
from urllib.parse import quote, urlparse


class UriHandler:
    """get/put objects under one backup destination."""

    def get(self, name: str) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, name: str, data: bytes) -> None:
        raise NotImplementedError


class FileHandler(UriHandler):
    def __init__(self, dirpath: str):
        self.dir = dirpath

    def get(self, name: str) -> Optional[bytes]:
        path = os.path.join(self.dir, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def put(self, name: str, data: bytes) -> None:
        os.makedirs(self.dir, exist_ok=True)
        tmp = os.path.join(self.dir, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.dir, name))


def _sigv4(method: str, host: str, uri: str, payload: bytes,
           access: str, secret: str, region: str) -> dict:
    """Minimal AWS Signature Version 4 for S3 path-style requests."""
    now = datetime.now(timezone.utc)
    amzdate = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amzdate}
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method, uri, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amzdate, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    key = f"AWS4{secret}".encode()
    for part in (datestamp, region, "s3", "aws4_request"):
        key = hmac.new(key, part.encode(), hashlib.sha256).digest()
    sig = hmac.new(key, to_sign.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    del headers["host"]  # http.client sets it
    return headers


class S3Handler(UriHandler):
    """Path-style S3 REST client (ref ee/backup/s3_handler.go)."""

    def __init__(self, endpoint: str, secure: bool, bucket: str,
                 prefix: str):
        self.endpoint = endpoint
        self.secure = secure
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access = os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self.region = os.environ.get("AWS_DEFAULT_REGION", "us-east-1")

    def _conn(self) -> http.client.HTTPConnection:
        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        return cls(self.endpoint, timeout=30)

    def _request(self, method: str, name: str,
                 payload: bytes = b"") -> tuple[int, bytes]:
        key = f"{self.prefix}/{name}" if self.prefix else name
        uri = "/" + quote(f"{self.bucket}/{key}")
        headers = {}
        if self.access and self.secret:
            headers = _sigv4(method, self.endpoint, uri, payload,
                             self.access, self.secret, self.region)
        conn = self._conn()
        try:
            conn.request(method, uri, body=payload or None,
                         headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def get(self, name: str) -> Optional[bytes]:
        status, body = self._request("GET", name)
        if status == 404:
            return None
        if status != 200:
            raise IOError(
                f"s3 GET {name!r} failed: {status} {body[:200]!r}")
        return body

    def put(self, name: str, data: bytes) -> None:
        status, body = self._request("PUT", name, data)
        if status not in (200, 201, 204):
            raise IOError(
                f"s3 PUT {name!r} failed: {status} {body[:200]!r}")


def new_uri_handler(dest: str) -> UriHandler:
    """Scheme dispatch (ref handler.go:159 NewUriHandler)."""
    u = urlparse(dest)
    if u.scheme in ("", "file"):
        return FileHandler(u.path or dest)
    if u.scheme in ("s3", "minio"):
        secure = "secure=true" in (u.query or "") or u.scheme == "s3"
        if u.scheme == "minio":
            endpoint = u.netloc
            parts = (u.path or "/").strip("/").split("/", 1)
            bucket = parts[0]
            prefix = parts[1] if len(parts) > 1 else ""
            if "secure=true" not in (u.query or ""):
                secure = False
        else:
            endpoint = os.environ.get("AWS_ENDPOINT",
                                      "s3.amazonaws.com")
            bucket = u.netloc
            prefix = (u.path or "").strip("/")
        if not bucket:
            raise ValueError(f"backup URI {dest!r} has no bucket")
        return S3Handler(endpoint, secure, bucket, prefix)
    raise ValueError(f"unknown backup URI scheme {u.scheme!r}")
