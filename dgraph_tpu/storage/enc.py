"""Encryption at rest (the ee/enc role).

The reference loads an AES key file and hands it to Badger for
block-level encryption (ee/enc/util_ee.go:24). Here the unit of
encryption is the durable blob: WAL record payloads, snapshot files,
and backup files are AES-128/192/256-GCM sealed per blob with a random
nonce. Key files are raw 16/24/32-byte keys, exactly like the
reference's --encryption_key_file.
"""

from __future__ import annotations

from typing import Optional

_MAGIC = b"DGTENC1\x00"


def load_key(path: str) -> bytes:
    with open(path, "rb") as f:
        key = f.read()
    if len(key) not in (16, 24, 32):
        raise ValueError(
            f"encryption key must be 16/24/32 bytes, got {len(key)} "
            "(ref ee/enc/util_ee.go ReadEncryptionKeyFile)")
    return key


def _aesgcm(key: bytes):
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(key)


def encrypt_blob(blob: bytes, key: Optional[bytes]) -> bytes:
    if key is None:
        return blob
    import os
    nonce = os.urandom(12)
    return _MAGIC + nonce + _aesgcm(key).encrypt(nonce, blob, b"")


def decrypt_blob(blob: bytes, key: Optional[bytes]) -> bytes:
    if not blob.startswith(_MAGIC):
        if key is not None:
            raise ValueError("store is not encrypted but a key was given")
        return blob
    if key is None:
        raise ValueError("store is encrypted; --encryption_key_file needed")
    nonce = blob[len(_MAGIC): len(_MAGIC) + 12]
    return _aesgcm(key).decrypt(nonce, blob[len(_MAGIC) + 12:], b"")


def is_encrypted(blob: bytes) -> bool:
    return blob.startswith(_MAGIC)
