"""Pure-Python stand-in for native.NativeKV (same API) used only when
the C++ runtime can't be built: dict + WAL-file persistence via the
wire-compatible _PyWal framer."""

from __future__ import annotations

import os
import pickle


class PyKV:
    def __init__(self, directory: str, sync: bool = False):
        from dgraph_tpu.storage.wal import _PyWal
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._m: dict[bytes, bytes] = {}
        snap = os.path.join(directory, "SNAPSHOT.py")
        if os.path.exists(snap):
            with open(snap, "rb") as f:
                self._m = pickle.load(f)
        self._wal = _PyWal(os.path.join(directory, "WAL"), sync)
        for blob in self._wal.replay():
            op, k, v = pickle.loads(blob)
            if op == 0:
                self._m[k] = v
            else:
                self._m.pop(k, None)

    def put(self, key: bytes, val: bytes):
        self._wal.append(pickle.dumps((0, key, val)))
        self._m[key] = val

    def delete(self, key: bytes):
        self._wal.append(pickle.dumps((1, key, None)))
        self._m.pop(key, None)

    def get(self, key: bytes):
        return self._m.get(key)

    def __len__(self):
        return len(self._m)

    def scan(self, prefix: bytes = b""):
        for k in sorted(self._m):
            if k.startswith(prefix):
                yield k, self._m[k]

    def flush(self):
        self._wal.flush()

    def snapshot(self):
        tmp = os.path.join(self._dir, "SNAPSHOT.py.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(self._m, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, "SNAPSHOT.py"))
        self._wal.truncate()

    def close(self):
        self._wal.close()
