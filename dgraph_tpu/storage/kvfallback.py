"""Pure-Python stand-in for native.NativeKV (same API) used only when
the C++ runtime can't be built: dict + WAL-file persistence via the
wire-compatible _PyWal framer. Records and snapshots are wire-encoded
(dgraph_tpu.wire) so a store written by this fallback stays readable by
any build; pre-wire pickle payloads are replayed once via
wire.loads_compat (the migration shim, tested in test_wire.py)."""

from __future__ import annotations

import os

from dgraph_tpu.wire import dumps as wire_dumps
from dgraph_tpu.wire import loads_compat as wire_loads_compat


class PyKV:
    def __init__(self, directory: str, sync: bool = False):
        from dgraph_tpu.storage.wal import _PyWal
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._m: dict[bytes, bytes] = {}
        snap = os.path.join(directory, "SNAPSHOT.py")
        if os.path.exists(snap):
            with open(snap, "rb") as f:
                self._m = wire_loads_compat(f.read())
        self._wal = _PyWal(os.path.join(directory, "WAL"), sync)
        for blob in self._wal.replay():
            op, k, v = wire_loads_compat(blob)
            if op == 0:
                self._m[k] = v
            else:
                self._m.pop(k, None)

    def put(self, key: bytes, val: bytes):
        self._wal.append(wire_dumps((0, key, val)))
        self._m[key] = val

    def delete(self, key: bytes):
        self._wal.append(wire_dumps((1, key, None)))
        self._m.pop(key, None)

    def get(self, key: bytes):
        return self._m.get(key)

    def __len__(self):
        return len(self._m)

    def scan(self, prefix: bytes = b""):
        for k in sorted(self._m):
            if k.startswith(prefix):
                yield k, self._m[k]

    def flush(self):
        self._wal.flush()

    def snapshot(self):
        tmp = os.path.join(self._dir, "SNAPSHOT.py.tmp")
        with open(tmp, "wb") as f:
            f.write(wire_dumps(self._m))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, "SNAPSHOT.py"))
        self._wal.truncate()

    def close(self):
        self._wal.close()
