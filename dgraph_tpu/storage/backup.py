"""Binary backup/restore with incremental manifest chains (ee/backup/).

The reference streams Badger keys with version > sinceTs to a URI
handler (file/S3/minio) and records a manifest chain; restore replays
the chain in order (ee/backup/backup.go:88 WriteBackup,
handler.go:159 NewUriHandler, restore.go:37).

Our unit of incremental change is the tablet: a backup serializes every
tablet whose max_commit_ts (or base_ts, post-rollup) moved past the
chain's last read_ts, plus the schema and coordinator watermarks.
Restore folds the chain newest-wins per tablet. Artifacts are
gzip-compressed wire payloads, optionally sealed with AES-GCM (storage/enc.py).

URI handlers (storage/uri.py, ref ee/backup/handler.go): file paths
and file:// everywhere; s3://bucket/prefix and minio://host:port/bucket
speak the S3 REST protocol with SigV4 from env credentials.
"""

from __future__ import annotations

import gzip
import json
import time
from typing import Optional

from dgraph_tpu.storage.enc import decrypt_blob, encrypt_blob
from dgraph_tpu.storage.uri import new_uri_handler

MANIFEST = "manifest.json"


def _read_chain(handler) -> list[dict]:
    raw = handler.get(MANIFEST)
    return json.loads(raw) if raw else []


def read_manifests(dest: str) -> list[dict]:
    return _read_chain(new_uri_handler(dest))


def backup(db, dest: str, force_full: bool = False,
           key: Optional[bytes] = None) -> dict:
    """Write a full or incremental backup; returns its manifest entry.
    Incremental = tablets whose state moved past the chain's last
    read_ts (ref backup.go Request.since logic)."""
    handler = new_uri_handler(dest)
    chain = _read_chain(handler)
    since = 0 if (force_full or not chain) else chain[-1]["read_ts"]

    db.rollup_all(window=0)  # backups must capture every commit
    read_ts = db.coordinator.max_assigned()
    tablets = {}
    for pred, tab in db.tablets.items():
        moved = max(tab.max_commit_ts, tab.base_ts)
        if since and moved <= since:
            continue
        from dgraph_tpu.storage.snapshot import _gv_dict
        tablets[pred] = {
            "edges_gv": _gv_dict(tab.edges),
            "reverse_gv": _gv_dict(tab.reverse),
            "values": tab.values,
            "index_gv": _gv_dict(tab.index),
            "edge_facets": tab.edge_facets, "base_ts": tab.base_ts,
        }
    payload = {
        "schema": db.schema.describe_all(),
        "tablets": tablets,
        "read_ts": read_ts,
        "since_ts": since,
        "next_uid": db.coordinator._next_uid,
    }
    # predicates the chain believes exist but the store no longer has:
    # record them as dropped so restore doesn't resurrect deleted data
    # (drop_attr / drop_all between backups)
    chain_preds: set = set()
    for e in chain:
        chain_preds |= set(e.get("predicates", []))
        chain_preds -= set(e.get("dropped", []))
    dropped = sorted(chain_preds - set(db.tablets))

    name = f"backup-{since}-{read_ts}.gz"
    from dgraph_tpu import wire
    blob = gzip.compress(wire.dumps(payload))
    handler.put(name, encrypt_blob(blob, key))
    entry = {"type": "full" if since == 0 else "incremental",
             "since_ts": since, "read_ts": read_ts, "file": name,
             "encrypted": key is not None,
             # wall clock: manifest stamps are user-visible instants
             "unix_ts": int(time.time()),  # dglint: disable=DG06
             "predicates": sorted(tablets),
             "dropped": dropped}
    chain.append(entry)
    handler.put(MANIFEST, json.dumps(chain, indent=2).encode())
    return entry


def restore(dest: str, db=None, key: Optional[bytes] = None):
    """Rebuild an engine from the manifest chain, newest-wins per
    tablet (ref restore.go:37 RunRestore ordering)."""
    from dgraph_tpu.engine.db import GraphDB
    from dgraph_tpu.storage.tablet import Tablet

    handler = new_uri_handler(dest)
    chain = _read_chain(handler)
    if not chain:
        raise FileNotFoundError(f"no backup manifest under {dest!r}")
    db = db or GraphDB()
    max_ts = 0
    next_uid = 1
    for entry in chain:
        raw = handler.get(entry["file"])
        if raw is None:
            raise FileNotFoundError(
                f"backup artifact {entry['file']!r} missing from chain")
        from dgraph_tpu.storage.snapshot import _load_payload
        payload = _load_payload(gzip.decompress(decrypt_blob(raw, key)))
        db.alter(payload["schema"])
        from dgraph_tpu.storage.snapshot import _ungv_dict
        for pred, st in payload["tablets"].items():
            ps = db.schema.get_or_default(pred)
            tab = Tablet(pred, ps)
            # group-varint at-rest form, dense in pre-compression
            # chains (same migration seam as restore_tablet)
            tab.edges = _ungv_dict(st["edges_gv"]) \
                if "edges_gv" in st else st["edges"]
            tab.reverse = _ungv_dict(st["reverse_gv"]) \
                if "reverse_gv" in st else st["reverse"]
            tab.values = st["values"]
            tab.index = _ungv_dict(st["index_gv"]) \
                if "index_gv" in st else st["index"]
            tab.edge_facets = st["edge_facets"]
            tab.base_ts = st["base_ts"]
            db.tablets[pred] = tab
            db.coordinator.should_serve(pred)
        for pred in entry.get("dropped", []):
            db.tablets.pop(pred, None)
            db.schema.delete_predicate(pred)
        max_ts = max(max_ts, payload["read_ts"])
        next_uid = max(next_uid, payload["next_uid"])
    db.fast_forward_ts(max_ts)
    db.coordinator.bump_uids(next_uid - 1)
    return db
