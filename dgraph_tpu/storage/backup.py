"""Binary backup/restore with incremental manifest chains (ee/backup/),
plus point-in-time restore composed from the chain + captured CDC tail.

The reference streams Badger keys with version > sinceTs to a URI
handler (file/S3/minio) and records a manifest chain; restore replays
the chain in order (ee/backup/backup.go:88 WriteBackup,
handler.go:159 NewUriHandler, restore.go:37).

Our unit of incremental change is the tablet: a backup serializes every
tablet whose max_commit_ts (or base_ts, post-rollup) moved past the
chain's last read_ts, plus the schema and coordinator watermarks.
Restore folds the chain newest-wins per tablet. Artifacts are
gzip-compressed wire payloads, optionally sealed with AES-GCM
(storage/enc.py), stamped with the at-rest format_version
(storage/versions.py; unstamped legacy chains load as version 0).

Point-in-time restore (restore_to_ts): each backup also captures the
per-predicate RAW change-log tail (cdc/changelog.read_raw — original
EdgeOps, whole commits, ascending ts) covering its (since_ts, read_ts]
window. Restoring to an arbitrary commit_ts T replays the chain's
entries at or below T as the base, then applies the NEXT entry's
captured batches with commit_ts <= T through the SAME replicated-record
apply path a tablet move uses (("move_delta", ...) ->
engine/db.apply_record: tab.apply + cdc.append with identical offsets),
so the result is byte-identical to an oracle that replayed the whole
WAL and stopped at T. The raw ring is bounded (DEFAULT_RAW_CAP): when
eviction has moved past since_ts the entry records a per-predicate
coverage floor, and a target inside the uncovered window raises the
typed PitrCoverageError instead of silently under-restoring.

URI handlers (storage/uri.py, ref ee/backup/handler.go): file paths
and file:// everywhere; s3://bucket/prefix and minio://host:port/bucket
speak the S3 REST protocol with SigV4 from env credentials.
"""

from __future__ import annotations

import gzip
import json
import time
from typing import Optional

from dgraph_tpu.storage.enc import decrypt_blob, encrypt_blob
from dgraph_tpu.storage.uri import new_uri_handler
from dgraph_tpu.storage.versions import FORMAT_VERSION, check_format

MANIFEST = "manifest.json"


class PitrCoverageError(ValueError):
    """The restore target falls inside a window the chain cannot
    reconstruct: the bounded raw change ring had already evicted part
    of (base watermark, floor_ts] when the covering backup ran, so the
    replay from the base has a hole. Restore to a chain boundary
    instead; shorten the backup interval (or raise the raw ring cap)
    to keep windows fully covered."""

    def __init__(self, pred: str, have_ts: int, floor_ts: int,
                 to_ts: int):
        self.pred = pred
        self.have_ts = have_ts
        self.floor_ts = floor_ts
        self.to_ts = to_ts
        super().__init__(
            f"cannot restore {pred!r} to ts {to_ts}: the covering "
            f"backup's change capture starts at ts {floor_ts} but the "
            f"chain's base state ends at ts {have_ts} — commits in "
            f"({have_ts}, {floor_ts}] were evicted before the backup "
            f"ran; restore to a chain boundary (ts <= {have_ts} or "
            f"the covering entry's read_ts) instead")


def _read_chain(handler) -> list[dict]:
    raw = handler.get(MANIFEST)
    return json.loads(raw) if raw else []


def read_manifests(dest: str) -> list[dict]:
    return _read_chain(new_uri_handler(dest))


def _capture_changelog(db, pred: str, since_ts: int,
                       read_ts: int) -> tuple[list, int]:
    """Drain the predicate's RAW change ring for commits in
    (since_ts, read_ts]: [(commit_ts, [EdgeOp, ...]), ...] plus the
    coverage floor — since_ts when the ring still held the whole
    window, else the eviction point (commits at or below it are only
    in the base state, not replayable)."""
    from dgraph_tpu.cdc.changelog import OffsetTruncated, offset_for_ts
    after = offset_for_ts(since_ts)
    floor_ts = since_ts
    batches: list = []
    while True:
        try:
            got = db.cdc.read_raw(pred, after=after, limit=1024)
        except OffsetTruncated as e:
            # the bounded ring evicted past since_ts: coverage starts
            # at the eviction point; anything gathered below is moot
            after = e.floor
            floor_ts = max(floor_ts, e.resync_ts)
            batches = []
            continue
        fresh = [(int(ts), list(ops)) for ts, ops in got["batches"]]
        batches.extend(fresh)
        if not fresh:
            break
        after = offset_for_ts(batches[-1][0])
    # a commit racing the backup can land past read_ts mid-capture:
    # keep the entry self-consistent with its stamped window
    return [b for b in batches if b[0] <= read_ts], floor_ts


def backup(db, dest: str, force_full: bool = False,
           key: Optional[bytes] = None) -> dict:
    """Write a full or incremental backup; returns its manifest entry.
    Incremental = tablets whose state moved past the chain's last
    read_ts (ref backup.go Request.since logic). Tablets ship in the
    dump_tablet shape (storage/snapshot.py — the one wire shape shared
    by snapshots, moves and the cold store), so backups carry the full
    fidelity restore needs: unfolded deltas, commit watermarks and
    trained ANN codebooks included."""
    from dgraph_tpu.storage.snapshot import dump_tablet
    handler = new_uri_handler(dest)
    chain = _read_chain(handler)
    since = 0 if (force_full or not chain) else chain[-1]["read_ts"]

    db.rollup_all(window=0)  # backups must capture every commit
    read_ts = db.coordinator.max_assigned()
    tablets = {}
    changelog = {}
    changelog_floor = {}
    for pred, tab in db.tablets.items():
        moved = max(tab.max_commit_ts, tab.base_ts)
        if since and moved <= since:
            continue
        tablets[pred] = dump_tablet(tab)
        batches, floor_ts = _capture_changelog(db, pred, since, read_ts)
        changelog[pred] = batches
        changelog_floor[pred] = floor_ts
    payload = {
        "format_version": FORMAT_VERSION,
        "schema": db.schema.describe_all(),
        "tablets": tablets,
        # the PITR tail: raw per-predicate change batches covering
        # (changelog_floor[pred], read_ts] — restore_to_ts replays
        # them through the move_delta apply path
        "changelog": changelog,
        "changelog_floor": changelog_floor,
        "read_ts": read_ts,
        "since_ts": since,
        "next_uid": db.coordinator._next_uid,
    }
    # predicates the chain believes exist but the store no longer has:
    # record them as dropped so restore doesn't resurrect deleted data
    # (drop_attr / drop_all between backups)
    chain_preds: set = set()
    for e in chain:
        chain_preds |= set(e.get("predicates", []))
        chain_preds -= set(e.get("dropped", []))
    dropped = sorted(chain_preds - set(db.tablets))

    name = f"backup-{since}-{read_ts}.gz"
    from dgraph_tpu import wire
    blob = gzip.compress(wire.dumps(payload))
    handler.put(name, encrypt_blob(blob, key))
    entry = {"type": "full" if since == 0 else "incremental",
             "format_version": FORMAT_VERSION,
             "since_ts": since, "read_ts": read_ts, "file": name,
             "encrypted": key is not None,
             # wall clock: manifest stamps are user-visible instants
             "unix_ts": int(time.time()),  # dglint: disable=DG06
             "predicates": sorted(tablets),
             "dropped": dropped}
    chain.append(entry)
    handler.put(MANIFEST, json.dumps(chain, indent=2).encode())
    return entry


def _entry_payload(handler, entry: dict,
                   key: Optional[bytes]) -> dict:
    raw = handler.get(entry["file"])
    if raw is None:
        raise FileNotFoundError(
            f"backup artifact {entry['file']!r} missing from chain")
    from dgraph_tpu.storage.snapshot import _load_payload
    payload = _load_payload(gzip.decompress(decrypt_blob(raw, key)))
    check_format(payload.get("format_version", 0),
                 f"backup artifact {entry['file']!r}")
    return payload


def _apply_entry(payload: dict, db) -> None:
    """Fold one chain entry into the engine, newest-wins per tablet.
    Handles every historical tablet shape through restore_tablet's
    migration seams (raw `values`, dense pre-compression arrays)."""
    from dgraph_tpu.storage.snapshot import restore_tablet
    db.alter(payload["schema"])
    for pred, st in payload["tablets"].items():
        ps = db.schema.get_or_default(pred)
        tab = restore_tablet(pred, ps, st)
        db.tablets[pred] = tab
        db.coordinator.should_serve(pred)
        # same floor contract as restore_state: history at or below
        # the restored watermark lives in the base state, not the log
        db.cdc.reset_floor(pred, max(tab.max_commit_ts, tab.base_ts))


def restore(dest: str, db=None, key: Optional[bytes] = None):
    """Rebuild an engine from the manifest chain, newest-wins per
    tablet (ref restore.go:37 RunRestore ordering)."""
    from dgraph_tpu.engine.db import GraphDB

    handler = new_uri_handler(dest)
    chain = _read_chain(handler)
    if not chain:
        raise FileNotFoundError(f"no backup manifest under {dest!r}")
    db = db or GraphDB()
    max_ts = 0
    next_uid = 1
    for entry in chain:
        payload = _entry_payload(handler, entry, key)
        _apply_entry(payload, db)
        for pred in entry.get("dropped", []):
            db.tablets.pop(pred, None)
            db.schema.delete_predicate(pred)
        max_ts = max(max_ts, payload["read_ts"])
        next_uid = max(next_uid, payload["next_uid"])
    db.fast_forward_ts(max_ts)
    db.coordinator.bump_uids(next_uid - 1)
    return db


def restore_to_ts(dest: str, to_ts: int, db=None,
                  key: Optional[bytes] = None):
    """Point-in-time restore: materialize the store as of commit_ts
    `to_ts` — ANY committed instant the chain covers, not just backup
    boundaries. Chain entries with read_ts <= to_ts restore as the
    base; the next entry's captured change batches replay on top
    through the move_delta apply path (identical tablet state AND CDC
    offsets to a full-WAL oracle replay stopped at to_ts — the parity
    tools/dr_smoke.py gates). Raises PitrCoverageError when to_ts
    falls in a window the bounded raw ring had evicted before the
    covering backup ran, and ValueError for targets past the chain
    head or under a version-0 (pre-capture) covering entry."""
    from dgraph_tpu.engine.db import GraphDB

    handler = new_uri_handler(dest)
    chain = _read_chain(handler)
    if not chain:
        raise FileNotFoundError(f"no backup manifest under {dest!r}")
    to_ts = int(to_ts)
    head_ts = chain[-1]["read_ts"]
    if to_ts > head_ts:
        raise ValueError(
            f"cannot restore to ts {to_ts}: the chain ends at read_ts "
            f"{head_ts}; run a newer backup first")
    db = db or GraphDB()
    next_uid = 1
    base_top = 0
    for entry in chain:
        if entry["read_ts"] > to_ts:
            break
        payload = _entry_payload(handler, entry, key)
        _apply_entry(payload, db)
        for pred in entry.get("dropped", []):
            db.tablets.pop(pred, None)
            db.schema.delete_predicate(pred)
        base_top = max(base_top, payload["read_ts"])
        next_uid = max(next_uid, payload["next_uid"])
    if to_ts > base_top:
        # to_ts sits strictly inside the NEXT entry's window: replay
        # its captured tail up to the target
        tail = next(e for e in chain if e["read_ts"] > to_ts)
        payload = _entry_payload(handler, tail, key)
        changelog = payload.get("changelog")
        if changelog is None:
            raise ValueError(
                f"backup {tail['file']!r} predates change capture "
                f"(format_version 0): restore only to chain "
                f"boundaries, nearest are ts {base_top} and "
                f"{tail['read_ts']}")
        db.alter(payload["schema"])
        floors = payload.get("changelog_floor", {})
        for pred in sorted(changelog):
            have = db.tablets[pred].max_commit_ts \
                if pred in db.tablets else 0
            floor_ts = int(floors.get(pred, tail["since_ts"]))
            if floor_ts > have:
                # commits in (have, floor_ts] were evicted before the
                # covering backup ran — nothing can reconstruct them
                raise PitrCoverageError(pred, have, floor_ts, to_ts)
            batches = [(ts, ops) for ts, ops in changelog[pred]
                       if have < ts <= to_ts]
            if batches:
                db.apply_record(("move_delta", pred, batches))
        next_uid = max(next_uid, payload["next_uid"])
    db.fast_forward_ts(to_ts)
    # the tail entry's uid watermark may exceed what existed at to_ts;
    # over-reserving is safe (no allocation below it can collide),
    # under-reserving is not — move_delta already bumped per-op uids
    db.coordinator.bump_uids(next_uid - 1)
    return db
