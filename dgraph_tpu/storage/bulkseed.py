"""Bulk store seeding: synthesize cold-store blobs without apply().

The per-edge ingest path (mutate -> overlay delta -> rollup fold) costs
microseconds per triple in Python — honest for OLTP, hopeless for
standing up a 500M-edge regime (BENCH_500M, tools/bench_500m.py) where
seeding would take days. The reference has the same split: live writes
go through the Raft/posting pipeline while dgraph bulk (bulk/loader.go,
bulk/reduce.go) writes finished Badger SSTs directly. This module is
that bulk lane: it builds the EXACT wire payload TabletStore.save would
have produced for a rolled-up tablet — group-varint uid planes, packed
value columns, token index — straight from numpy arrays, and puts it
into the KV. A store seeded here is indistinguishable from one grown
through mutations: restore_tablet materializes it, the prefetch pipeline
decodes it, parity oracles read it.

Invariants the synthesizer must honor (or lazy loads go subtly wrong):
  - every uid vector (edges, reverse, index postings) sorted ascending;
  - index keys carry the tokenizer identifier byte (utils/keys.token_bytes)
    exactly as Tablet._tokens would emit them;
  - values_pk columns are parallel and walk src in ascending-uid order
    (the deterministic dict order _pack_values would have produced);
  - base_ts == max_commit_ts and meta:max_ts saved at or above it,
    else every read on the reopened store is a StaleSnapshot.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from dgraph_tpu import wire
from dgraph_tpu.models.tokenizer import get_tokenizer
from dgraph_tpu.models.types import TypeID
from dgraph_tpu.utils.keys import token_bytes

_TAB_PREFIX = b"tab:"


def _split_sorted(uids: np.ndarray, codes: np.ndarray):
    """Group sorted-ascending `uids` by parallel `codes`: yields
    (code, uid_subset) with each subset still ascending (stable sort on
    codes preserves the uid order inside a group)."""
    order = np.argsort(codes, kind="stable")
    sc = codes[order]
    su = uids[order]
    bounds = np.flatnonzero(np.diff(sc)) + 1
    starts = np.concatenate(([0], bounds))
    ends = np.concatenate((bounds, [len(sc)]))
    for s, e in zip(starts.tolist(), ends.tolist()):
        yield sc[s], su[s:e]


def _index_gv(tokenizers, tid: TypeID, uids: np.ndarray,
              codes: np.ndarray, decode) -> dict:
    """Token index plane for a single-tokenizer value column. `codes`
    is the per-uid value in token space already (int key or label id);
    `decode(code)` maps it to the tokenizer's token (int or str)."""
    from dgraph_tpu.ops.codec import gv_encode
    out: dict[bytes, bytes] = {}
    for tname in tokenizers:
        spec = get_tokenizer(tname)
        for code, sub in _split_sorted(uids, codes):
            out[token_bytes(spec.ident, decode(code))] = gv_encode(sub)
    return out


def _blob(schema_text: str, tablet: dict) -> bytes:
    return wire.dumps({"schema": schema_text, "tablet": tablet})


def _base(base_ts: int) -> dict:
    return {"reverse_gv": {}, "edge_facets": {}, "deltas": [],
            "base_ts": int(base_ts), "max_commit_ts": int(base_ts)}


def int_tablet_blob(schema_text: str, uids: np.ndarray,
                    vals: np.ndarray, base_ts: int,
                    tokenizers=("int",)) -> bytes:
    """int-valued predicate: one posting per uid, @index(int)."""
    uids = np.asarray(uids, np.uint64)
    vals = np.asarray(vals, np.int64)
    tab = _base(base_ts)
    tab["edges_gv"] = {}
    tab["values_pk"] = {"src": uids, "tid": bytes([int(TypeID.INT)]) * len(uids),
                        "pay": vals.tolist(), "lang": [], "facets": []}
    tab["index_gv"] = _index_gv(tokenizers, TypeID.INT, uids, vals,
                                lambda c: int(c))
    return _blob(schema_text, tab)


def str_tablet_blob(schema_text: str, uids: np.ndarray,
                    labels: list[str], codes: np.ndarray, base_ts: int,
                    tokenizers=("exact",)) -> bytes:
    """string-valued predicate: per-uid label picked by `codes` into
    `labels`, @index(exact) (or any string tokenizer set)."""
    uids = np.asarray(uids, np.uint64)
    codes = np.asarray(codes, np.int64)
    tab = _base(base_ts)
    tab["edges_gv"] = {}
    pay = [labels[c] for c in codes.tolist()]
    tab["values_pk"] = {"src": uids,
                        "tid": bytes([int(TypeID.STRING)]) * len(uids),
                        "pay": pay, "lang": [], "facets": []}
    tab["index_gv"] = _index_gv(tokenizers, TypeID.STRING, uids, codes,
                                lambda c: labels[int(c)])
    return _blob(schema_text, tab)


def uid_tablet_blob(schema_text: str, srcs: np.ndarray,
                    indptr: np.ndarray, dsts: np.ndarray,
                    base_ts: int) -> bytes:
    """uid predicate from CSR form: srcs[i] owns dsts[indptr[i]:
    indptr[i+1]] (each row must already be sorted ascending)."""
    from dgraph_tpu.ops.codec import gv_encode
    srcs = np.asarray(srcs, np.uint64)
    dsts = np.asarray(dsts, np.uint64)
    tab = _base(base_ts)
    edges: dict[int, bytes] = {}
    ip = np.asarray(indptr, np.int64).tolist()
    for i, src in enumerate(srcs.tolist()):
        row = dsts[ip[i]:ip[i + 1]]
        if len(row):
            edges[int(src)] = gv_encode(row)
    tab["edges_gv"] = edges
    tab["values_pk"] = {"src": np.empty(0, np.uint64), "tid": b"",
                        "pay": [], "lang": [], "facets": []}
    tab["index_gv"] = {}
    return _blob(schema_text, tab)


def seed_store(store, schema_text: str,
               blobs: Iterable[tuple[str, bytes]], max_ts: int) -> int:
    """Install synthesized blobs into a TabletStore: per-pred tablet
    payloads + the meta plane (schema text, coordinator high-water ts).
    Returns total bytes written. Call store.compact() afterwards so the
    WAL folds into one snapshot before the bench reopens the store."""
    total = 0
    for pred, blob in blobs:
        store.kv.put(_TAB_PREFIX + pred.encode("utf-8"), blob)
        total += len(blob)
    store.save_schema(schema_text)
    store.save_max_ts(int(max_ts))
    return total
