"""Write-ahead log: durability for committed transactions.

The reference persists every mutation through Badger's value log +
Raft WAL (raftwal/storage.go over Badger). Round-1 equivalent: an
append-only record log with length-prefixed pickled commit records and
an fsync policy; the engine replays it at open. Raft replication plugs
in above this (cluster/), snapshotting truncates it (ref
worker/draft.go:1206 calculateSnapshot).
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Iterator

_MAGIC = b"DGTWAL1\x00"


class Wal:
    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        exists = os.path.exists(path)
        self._f = open(path, "ab+")
        if not exists or self._f.tell() == 0:
            self._f.write(_MAGIC)
            self._f.flush()

    def append(self, record: Any):
        blob = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
        self._f.write(struct.pack("<I", len(blob)))
        self._f.write(blob)
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())

    def replay(self) -> Iterator[Any]:
        with open(self.path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise IOError(f"bad WAL magic in {self.path}")
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break
                (n,) = struct.unpack("<I", hdr)
                blob = f.read(n)
                if len(blob) < n:
                    break  # torn tail write: ignore, next append overwrites
                yield pickle.loads(blob)

    def truncate(self):
        """Reset after a snapshot has captured state (ref raft WAL
        truncation below snapshot index, raftwal/storage.go:594)."""
        self._f.close()
        self._f = open(self.path, "wb")
        self._f.write(_MAGIC)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f = open(self.path, "ab+")

    def close(self):
        self._f.close()
