"""Write-ahead log: durability for committed transactions.

The reference persists every mutation through Badger's value log + Raft
WAL (raftwal/storage.go over Badger). Here the framing, CRC validation,
torn-tail truncation, and fsync policy live in the native C++ runtime
(native/native.cc dgt_wal_*, bound via dgraph_tpu.native.NativeWal);
records are wire-encoded engine commit tuples. A pure-Python framer backs it
up when the native library cannot be built. Raft replication plugs in
above this (cluster/), snapshotting truncates it (ref worker/draft.go:1206
calculateSnapshot).
"""

from __future__ import annotations

import os
import struct
from typing import Any, Iterator

from dgraph_tpu import native

# Same on-disk format as native/native.cc (kWalMagic / frame =
# u32 len | u32 crc32 | payload): the two backends are interchangeable
# on the same file, so a store created with the native lib still opens
# if the toolchain later disappears, and vice versa.
_MAGIC = b"DGTWAL2\x00"
_LEGACY_MAGIC = b"DGTWAL1\x00"


def _timed_fsync(fd: int) -> None:
    """fsync + dgraph_wal_fsync_seconds observation: the watchdog's
    wal_fsync_stall rule reads this histogram's tick-window p99 — a
    dying durability volume shows here long before the engine
    visibly stalls. Seconds (own bucket table in metrics.py
    BUCKETS_BY_NAME), not the default ms buckets."""
    import time

    from dgraph_tpu.utils import metrics
    t0 = time.perf_counter()
    os.fsync(fd)
    metrics.observe("dgraph_wal_fsync_seconds",
                    time.perf_counter() - t0)


def raise_if_legacy_wal(path: str) -> None:
    """Pre-CRC DGTWAL1 files must fail with a recovery path, not a bare
    'bad magic' / bricked store (advisor finding). Shared by both WAL
    backends so the format knowledge lives in one place."""
    try:
        with open(path, "rb") as f:
            legacy = f.read(len(_LEGACY_MAGIC)) == _LEGACY_MAGIC
    except OSError:
        return
    if legacy:
        raise IOError(
            f"{path} uses the legacy DGTWAL1 format; export/snapshot "
            "it with a pre-DGTWAL2 build, then restore into a fresh "
            "store")


class _PyWal:
    """Fallback framer, wire-compatible with dgt_wal_*."""
    # dglint: guarded-by=*:external (appends happen only on the
    # engine's serialized write path; replay/close are lifecycle-edge
    # calls — synchronization is the caller's contract)

    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        exists = os.path.exists(path)
        self._f = open(path, "ab+")
        if not exists or self._f.tell() == 0:
            self._f.write(_MAGIC)
            self._f.flush()

    def append(self, blob: bytes):
        import zlib
        self._f.write(struct.pack("<II", len(blob),
                                  zlib.crc32(blob) & 0xFFFFFFFF))
        self._f.write(blob)
        self._f.flush()
        if self.sync:
            _timed_fsync(self._f.fileno())

    def replay(self):
        import zlib
        records = []
        with open(self.path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic == _LEGACY_MAGIC:
                raise_if_legacy_wal(self.path)
            if magic != _MAGIC:
                raise IOError(f"bad WAL magic in {self.path}")
            good = f.tell()
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                n, crc = struct.unpack("<II", hdr)
                blob = f.read(n)
                if len(blob) < n or (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
                    break  # torn/corrupt tail
                records.append(blob)
                good = f.tell()
        self._f.flush()
        size = os.path.getsize(self.path)
        if good < size:
            self._f.close()
            with open(self.path, "rb+") as f:
                f.truncate(good)
            self._f = open(self.path, "ab+")
        return records

    def truncate(self):
        self._f.close()
        self._f = open(self.path, "wb")
        self._f.write(_MAGIC)
        self._f.flush()
        _timed_fsync(self._f.fileno())
        self._f = open(self.path, "ab+")

    def flush(self):
        self._f.flush()
        _timed_fsync(self._f.fileno())

    def close(self):
        self._f.close()


def _decode_record(blob: bytes) -> Any:
    """Records are wire-encoded (dgraph_tpu.wire, version-tagged first
    byte); stores written before the wire format existed used pickle —
    wire.loads_compat (the one migration shim) replays those too so an
    upgrade never bricks a WAL."""
    from dgraph_tpu.wire import loads_compat
    return loads_compat(blob)


class Wal:
    """Record log for engine commits; native-backed when available.
    With `key`, every record blob is AES-GCM sealed before framing
    (encryption at rest, storage/enc.py; ref ee/enc)."""

    def __init__(self, path: str, sync: bool = False,
                 key: bytes | None = None):
        self.path = path
        self.sync = sync
        self.key = key
        if native.available():
            self._w = native.NativeWal(path, sync)
            self.native = True
        else:
            self._w = _PyWal(path, sync)
            self.native = False

    def append(self, record: Any):
        from dgraph_tpu.storage.enc import encrypt_blob
        from dgraph_tpu.utils import failpoint
        from dgraph_tpu.utils.tracing import span as _span
        from dgraph_tpu.wire import dumps
        with _span("wal.append") as sp:
            # chaos seam: delay/fail durability — an armed error here
            # models a full disk / dying volume before the frame lands
            failpoint.fire("wal.append")
            blob = encrypt_blob(dumps(record), self.key)
            sp["bytes"] = len(blob)
            if self.native and self.sync:
                # the native backend fsyncs inside dgt_wal_append —
                # time the whole durable append (fsync dominates it)
                # so the stall histogram covers both backends
                import time

                from dgraph_tpu.utils import metrics
                t0 = time.perf_counter()
                self._w.append(blob)
                metrics.observe("dgraph_wal_fsync_seconds",
                                time.perf_counter() - t0)
            else:
                self._w.append(blob)

    def replay(self) -> Iterator[Any]:
        from dgraph_tpu.storage.enc import decrypt_blob
        for blob in self._w.replay():
            yield _decode_record(decrypt_blob(blob, self.key))

    def truncate(self):
        """Reset after a snapshot has captured state (ref raft WAL
        truncation below snapshot index, raftwal/storage.go:594)."""
        self._w.truncate()

    def flush(self):
        if hasattr(self._w, "flush"):
            self._w.flush()

    def close(self):
        self._w.close()
