"""Streaming input chunkers.

Re-provides chunker/chunk.go: batch a large RDF or JSON input into
NQuad chunks without materializing the file (gzip transparent, format
autodetect). The reference chunks RDF by line count and JSON by
top-level array elements (chunker/chunk.go:95,164); same here.
"""

from __future__ import annotations

import gzip
import io
import json
from typing import Iterator

from dgraph_tpu.gql.nquad import NQuad, parse_json_mutation, parse_rdf

DEFAULT_CHUNK_LINES = 1000  # ref chunker/chunk.go batch size


def detect_format(path: str) -> str:
    """'rdf' | 'json' from filename (.gz transparent).
    Ref chunker.DataFormat (chunker/chunk.go:38)."""
    p = path[:-3] if path.endswith(".gz") else path
    if p.endswith((".rdf", ".nq", ".nt")):  # N-Quads/N-Triples only —
        return "rdf"                        # Turtle directives unsupported
    if p.endswith(".json"):
        return "json"
    raise ValueError(f"cannot detect format of {path!r} (use .rdf/.json)")


def _open(path: str) -> io.TextIOBase:
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"))
    return open(path, "r")


class Chunker:
    """Iterate NQuad batches from a stream."""

    def __init__(self, fmt: str, chunk_lines: int = DEFAULT_CHUNK_LINES):
        if fmt not in ("rdf", "json"):
            raise ValueError(f"bad format {fmt!r}")
        self.fmt = fmt
        self.chunk_lines = chunk_lines

    def chunks(self, f: io.TextIOBase) -> Iterator[list[NQuad]]:
        if self.fmt == "rdf":
            yield from self._rdf_chunks(f)
        else:
            yield from self._json_chunks(f)

    def _rdf_chunks(self, f) -> Iterator[list[NQuad]]:
        batch: list[str] = []
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            batch.append(line)
            if len(batch) >= self.chunk_lines:
                yield parse_rdf("\n".join(batch))
                batch = []
        if batch:
            yield parse_rdf("\n".join(batch))

    def _json_chunks(self, f) -> Iterator[list[NQuad]]:
        # stream top-level array elements without loading the whole file
        # (ref chunker/chunk.go:164 jsonChunker state machine)
        data = json.load(f)  # graphs fit host RAM in our deployments;
        # element-level streaming is a bulk-loader concern, chunk here
        items = data if isinstance(data, list) else [data]
        counter = [0]
        for i in range(0, len(items), self.chunk_lines):
            out: list[NQuad] = []
            for obj in items[i: i + self.chunk_lines]:
                out.extend(parse_json_mutation(obj, _counter=counter))
            yield out


def chunk_file(path: str, fmt: str = "",
               chunk_lines: int = DEFAULT_CHUNK_LINES
               ) -> Iterator[list[NQuad]]:
    fmt = fmt or detect_format(path)
    with _open(path) as f:
        yield from Chunker(fmt, chunk_lines).chunks(f)
