"""Distributed ingest: cluster-parallel map → shuffle → reduce.

The single-core loader (ingest/bulk.py) is one process end to end; at
scale its reduce is the bottleneck (ROADMAP item 3) and its map is
GIL-bound. This module parallelizes the whole pipeline the way the
reference's bulk loader does (bulk/mapper.go fan-out → reduce shards →
out/<i>/p Badger dirs), with the Coded-TeraSort map→shuffle→reduce
shape (PAPERS.md) over the repo's own wire framing:

  driver    owns the input: streams line-aligned text chunks to map
            workers in file order, pre-assigning blank-node uids with
            the sharded, lock-striped XidMap (ingest/xidmap.py) so uid
            assignment is deterministic and IDENTICAL to the
            single-core loader's on blank-node inputs — the bench's
            byte-parity oracle depends on it.
  workers   (N processes) parse chunks through the exact python
            grammar (gql/nquad.parse_rdf), partition every statement
            by predicate → reduce group, and STREAM the per-predicate
            parts to the owning group's reducer over wire-framed
            sockets (the shuffle). Chunk delivery is transactional:
            chunk_begin → parts → chunk_commit, so a worker SIGKILLed
            mid-shuffle leaves only uncommitted staging behind and the
            reassigned chunk re-streams idempotently — the retried
            shard reduces to BYTE-IDENTICAL output.
  reducers  (one process per group) spill committed parts to
            per-predicate run files, then reduce each predicate with
            the SAME kernel the single-core loader uses
            (bulk.reduce_predicate: segmented lexsort + unique,
            in-file-order value merges) and write the group's tablets
            straight into a bootable group-varint snapshot
            (storage/snapshot.py `edges_gv`/`reverse_gv`/`index_gv` at
            rest — no second encode pass): `g<k>/p.snap` boots an
            Alpha group via `node --snapshot` exactly like the
            single-core `bulk --reduce-shards` output.

Group partition: pred → crc32(pred) % groups (deterministic, no
coordination); the manifest records the realized tablet map and the
ts/uid watermarks Zero must honor at boot (bump_maxes, the same
contract as bulk_shard_outputs).

Chaos seams: `ingest.shuffle` fires before every part send,
`ingest.reduce` before every predicate's reduce (utils/failpoint.py).
"""

from __future__ import annotations

import json
import os
import queue
import re
import socket
import struct
import sys
import tempfile
import threading
import time
import zlib
from typing import Iterator, Optional

from dgraph_tpu import wire
from dgraph_tpu.utils import failpoint, metrics
from dgraph_tpu.utils.logger import log

_DEFAULT_CHUNK_BYTES = 1 << 20


def pred_group(pred: str, groups: int) -> int:
    """Deterministic predicate → reduce-group partition (1-based)."""
    return zlib.crc32(pred.encode()) % groups + 1


def _rpc(sock: socket.socket, req: dict) -> dict:
    wire.write_frame(sock, wire.dumps(req))
    return wire.loads(wire.read_frame(sock))


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

# blank-node labels, scanned OUTSIDE quoted literals (see _chunk_xids)
_BLANK_RE = re.compile(r"_:[A-Za-z0-9_.\-]+")
# explicit numeric uid refs (<0x5> / <123>): their high-water mark must
# bump the driver's lease counter BEFORE later blank assignments, the
# same ordering contract the single-core map loop keeps
_EXPLICIT_RE = re.compile(r"<(0[xX][0-9a-fA-F]+|[0-9]+)>")
# one C-speed pass blanks out quoted literals (escape-aware) so the
# ref scans below can run over the WHOLE chunk in document order —
# a per-line python loop here was the map phase's serial bottleneck
_QUOTED_RE = re.compile(r'"(?:[^"\\]|\\.)*"')


class _ExecProc:
    """subprocess.Popen behind the multiprocessing.Process lifecycle
    surface the driver uses (is_alive/terminate/kill/join/pid)."""

    def __init__(self, popen):
        self._p = popen
        self.pid = popen.pid

    def is_alive(self) -> bool:
        return self._p.poll() is None

    def terminate(self):
        self._p.terminate()

    def kill(self):
        self._p.kill()

    def join(self, timeout=None):
        try:
            self._p.wait(timeout=timeout)
        except Exception:  # noqa: BLE001 — join() never raises
            pass


class IngestDriver:
    """Owns one distributed load end to end: chunk streaming, xid
    assignment, worker/reducer lifecycle, the manifest. `workers=N`
    spawns N map processes (in_process=True runs them as threads over
    the same sockets — the unit-test mode; thread maps are GIL-bound
    and prove protocol correctness, not speed)."""

    def __init__(self, paths, schema: str = "", *, groups: int = 2,
                 workers: int = 2, outdir: str,
                 chunk_bytes: int = _DEFAULT_CHUNK_BYTES,
                 in_process: bool = False,
                 timeout_s: float = 600.0,
                 custom_tokenizers: tuple = ()):
        self.paths = list(paths)
        self.schema = schema
        # plugin tokenizer files: reducers run db.alter + index
        # rebuilds in THEIR OWN processes, so the paths must ride the
        # reduce command and load there — registering them in the
        # driver alone would fail every @index(<plugin>) schema
        self.custom_tokenizers = tuple(custom_tokenizers)
        self.groups = groups
        self.workers = workers
        self.outdir = outdir
        self.chunk_bytes = chunk_bytes
        self.in_process = in_process
        self.timeout_s = timeout_s

        from dgraph_tpu.cluster.coordinator import Coordinator
        from dgraph_tpu.ingest.xidmap import XidMap
        self._coord = Coordinator()
        self._xidmap = XidMap(self._coord)
        # producer-thread-only read cache over the XidMap: one plain
        # dict hit per label OCCURRENCE, the striped-lock assign only
        # per NEW label (the resolve RPC path goes straight to the
        # XidMap, which dedupes — no coherence issue)
        self._xid_cache: dict[str, int] = {}
        self._bumped = 0

        self._lock = threading.Lock()
        # producer thread pre-scans chunks into this bounded queue so
        # the xid scan overlaps worker parses instead of serializing
        # them behind the next_chunk lock (None = exhausted sentinel)
        self._chunk_q: queue.Queue = queue.Queue(maxsize=8)
        self._requeued: list[tuple[int, str, dict]] = []
        self._pending: dict[int, tuple[str, dict]] = {}  # id -> payload
        self._assigned: dict[int, set[int]] = {}  # conn id -> chunk ids
        self._done_chunks = 0
        self._map_exhausted = False
        self._reducers: dict[int, tuple[str, int]] = {}
        self._want_inventory = False
        self._spill_sizes: dict[int, dict] = {}
        self._reduce_cmds: dict[int, dict] = {}
        self._reduce_done: dict[int, dict] = {}
        self._failed: Optional[str] = None
        self.stats = {"chunks": 0, "mapped": 0, "shuffled_bytes": 0,
                      "resolve_rpcs": 0}
        self.worker_procs: list = []  # mp.Process / threads
        self._reducer_procs: list = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.addr = self._listener.getsockname()

    # ------------------------------------------------------------ chunking

    def _chunk_iter(self) -> Iterator[str]:
        """Line-aligned text chunks across all inputs, in file order
        (gzip transparent — the same reader the single-core fast path
        uses, smaller blocks for work distribution)."""
        from dgraph_tpu.ingest.bulk import _raw_text_chunks
        for p in self.paths:
            yield from _raw_text_chunks(p, chunk_bytes=self.chunk_bytes)

    def _producer(self):
        """Serial chunk producer: read → xid pre-scan → queue. ONE
        thread, so assignment order stays chunk order (deterministic)
        while workers drain the queue concurrently."""
        try:
            for chunk_id, text in enumerate(self._chunk_iter()):
                xids = self._chunk_xids(text)
                with self._lock:
                    self.stats["chunks"] += 1
                self._chunk_q.put((chunk_id, text, xids))
        except Exception as e:  # noqa: BLE001 — fail the run, visibly
            with self._lock:
                self._failed = f"chunk producer: " \
                               f"{type(e).__name__}: {e}"
        finally:
            self._chunk_q.put(None)

    def _chunk_xids(self, text: str) -> dict:
        """Pre-assign every blank-node label in `text`, in textual
        order, via the shared lock-striped XidMap — the driver is the
        ONE place assignment order is serial, which is what makes
        worker-parallel maps produce the same uids as the single-core
        loader (subject scans before object on each line, lines in
        file order — finditer is document order). Quoted literals are
        blanked by one escape-aware regex pass first, so a label-
        looking string inside a value never assigns. Explicit numeric
        uids bump the lease high-water BEFORE this chunk's blank
        assignments (chunk granularity; the single-core loader
        interleaves per statement, so a chunk mixing explicit uids
        with blanks keeps correctness but not oracle uid-parity —
        blank-node-only inputs, the bulk-loader norm, stay exact).
        External non-numeric xids resolve through the worker's
        `resolve` RPC instead."""
        if '"' in text:
            text = _QUOTED_RE.sub('""', text)
        hi = 0
        for m in _EXPLICIT_RE.finditer(text):
            v = int(m.group(1), 0)
            if v > hi:
                hi = v
        with self._lock:
            if hi > self._bumped:
                self._coord.bump_uids(hi)
                self._bumped = hi
        out: dict[str, int] = {}
        cache = self._xid_cache
        for m in _BLANK_RE.finditer(text):
            xid = m.group(0)
            if xid not in out:
                uid = cache.get(xid)
                if uid is None:
                    uid = cache[xid] = self._xidmap.assign(xid)
                out[xid] = uid
        return out

    # ------------------------------------------------------------- control

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 daemon=True)
            t.start()
            with self._lock:
                self._threads.append(t)

    def _conn_loop(self, conn: socket.socket):
        cid = id(conn)
        try:
            while not self._stop.is_set():
                req = wire.loads(wire.read_frame(conn))
                wire.write_frame(conn, wire.dumps(self._handle(cid,
                                                               req)))
        except (EOFError, OSError, wire.WireError):
            pass
        finally:
            conn.close()
            # a dead worker's in-flight chunks go back to the queue
            with self._lock:
                for chunk_id in self._assigned.pop(cid, set()):
                    payload = self._pending.get(chunk_id)
                    if payload is not None:
                        self._requeued.append(
                            (chunk_id, payload[0], payload[1]))

    def _handle(self, cid: int, req: dict) -> dict:
        op = req.get("op")
        if op == "hello":
            with self._lock:
                ready = len(self._reducers) == self.groups
                shuffle = {g: list(a)
                           for g, a in self._reducers.items()}
            return {"ok": True, "ready": ready, "groups": self.groups,
                    "shuffle": shuffle}
        if op == "register_reducer":
            with self._lock:
                self._reducers[int(req["group"])] = tuple(req["addr"])
            return {"ok": True}
        if op == "next_chunk":
            # dequeue AND book-keep under ONE lock hold: a chunk
            # popped but not yet in _pending would let a racing
            # thread's sentinel flip _map_exhausted and the driver
            # declare the map complete with that chunk unmapped —
            # silent data loss in the reduced shards (review finding)
            with self._lock:
                if self._requeued:
                    item = self._requeued.pop(0)
                elif self._map_exhausted:
                    return {"ok": True, "done": True}
                else:
                    try:
                        item = self._chunk_q.get_nowait()
                    except queue.Empty:
                        return {"ok": True, "wait": True}
                    if item is None:  # producer's exhausted sentinel
                        self._map_exhausted = True
                        return {"ok": True, "done": True}
                chunk_id, text, xids = item
                self._pending[chunk_id] = (text, xids)
                self._assigned.setdefault(cid, set()).add(chunk_id)
            return {"ok": True, "chunk": chunk_id, "text": text,
                    "xids": xids}
        if op == "resolve":
            # scanner-missed labels (escaped-quote lines, external
            # xids): first-seen order is RPC arrival here — correct,
            # just not oracle-uid-identical
            with self._lock:
                self.stats["resolve_rpcs"] += 1
                uids = {x: self._xidmap.assign(str(x))
                        for x in req["xids"]}
            return {"ok": True, "uids": uids}
        if op == "chunk_done":
            with self._lock:
                self._pending.pop(int(req["chunk"]), None)
                self._assigned.get(cid, set()).discard(
                    int(req["chunk"]))
                self._done_chunks += 1
                st = req.get("stats", {})
                self.stats["mapped"] += int(st.get("mapped", 0))
                self.stats["shuffled_bytes"] += int(
                    st.get("shuffled_bytes", 0))
                hi = int(st.get("max_uid", 0))
            with self._lock:
                if hi > self._bumped:
                    self._coord.bump_uids(hi)
                    self._bumped = max(self._bumped, hi)
            metrics.inc_counter("dgraph_ingest_mapped_total",
                                int(st.get("mapped", 0)))
            metrics.inc_counter("dgraph_ingest_shuffled_bytes_total",
                                int(st.get("shuffled_bytes", 0)))
            return {"ok": True}
        if op == "reducer_poll":
            g = int(req.get("group", 0))
            with self._lock:
                if self._failed:
                    return {"ok": True, "abort": self._failed}
                if len(self._reduce_done) == self.groups:
                    # every group reduced: reducers may tear down
                    # their shuffle listeners + spill files NOW — not
                    # before, because a slower peer may still be
                    # streaming rebalanced spill runs (fetch_spill)
                    # from this one
                    return {"ok": True, "exit": True}
                if g in self._reduce_done:
                    return {"ok": True, "wait": True}  # linger
                cmd = self._reduce_cmds.get(g)
                if cmd is not None:
                    return {"ok": True, "reduce": cmd}
                if self._want_inventory and g not in self._spill_sizes:
                    return {"ok": True, "inventory": True}
            return {"ok": True, "wait": True}
        if op == "spill_sizes":
            with self._lock:
                self._spill_sizes[int(req["group"])] = {
                    str(p): int(b)
                    for p, b in req.get("sizes", {}).items()}
            return {"ok": True}
        if op == "reduce_done":
            g = int(req["group"])
            with self._lock:
                self._reduce_done[g] = req.get("stats", {})
            metrics.inc_counter(
                "dgraph_ingest_reduced_total",
                int(req.get("stats", {}).get("reduced", 0)))
            return {"ok": True}
        if op == "failed":
            with self._lock:
                self._failed = str(req.get("error", "worker failed"))
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    # ------------------------------------------------------------- spawn

    def _spawn_procs(self):
        """Start map/reduce processes.

        REDUCERS always exec-spawn: they import the full engine (jax
        included), and a forked child inheriting a warm parent's
        native runtime state (BLAS pools, XLA threads) can deadlock —
        CPython warns exactly this, and it reproduced intermittently.
        Their ~2 s cold start overlaps the map phase completely.

        WORKERS fork when safe (driver jax-free AND single-threaded —
        run() forks BEFORE the accept/producer threads start, so no
        driver lock can be held mid-fork; children connect immediately
        because the listener's backlog queues them until the accept
        loop runs): their code path is the narrow numpy parse plane,
        and the warm interpreter shaves ~2 s off time-to-first-chunk.
        A jax-warm or threaded driver exec-spawns workers too."""
        import subprocess
        addr = f"{self.addr[0]}:{self.addr[1]}"
        env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"))
        env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        # DGRAPH_TPU_INGEST_DEBUG=1 lets child stderr through — the
        # operator's "why did my reducer die" switch
        sink = None if os.environ.get("DGRAPH_TPU_INGEST_DEBUG") \
            else subprocess.DEVNULL
        for g in range(1, self.groups + 1):
            self._reducer_procs.append(_ExecProc(subprocess.Popen(
                [sys.executable, "-m", "dgraph_tpu.ingest.distributed",
                 "reducer", addr, str(g)],
                env=env, stdout=sink, stderr=sink)))
        if "jax" not in sys.modules and threading.active_count() == 1:
            import multiprocessing
            ctx = multiprocessing.get_context("fork")
            for _ in range(self.workers):
                p = ctx.Process(target=run_worker, args=(addr,),
                                daemon=True)
                p.start()
                self.worker_procs.append(p)
            return
        for _ in range(self.workers):
            self.worker_procs.append(_ExecProc(subprocess.Popen(
                [sys.executable, "-m", "dgraph_tpu.ingest.distributed",
                 "worker", addr],
                env=env, stdout=sink, stderr=sink)))

    def _spawn_threads(self):
        addr = f"{self.addr[0]}:{self.addr[1]}"
        for g in range(1, self.groups + 1):
            t = threading.Thread(target=run_reducer, args=(addr, g),
                                 daemon=True)
            t.start()
            with self._lock:
                self._threads.append(t)
        for _ in range(self.workers):
            t = threading.Thread(target=run_worker, args=(addr,),
                                 daemon=True)
            t.start()
            with self._lock:
                self._threads.append(t)

    # --------------------------------------------------------------- run

    def run(self) -> dict:
        t0 = time.monotonic()
        # fork-safety contract: children fork BEFORE any driver
        # thread starts (see _spawn_procs); their first RPCs queue in
        # the listener backlog until the accept loop is up
        if not self.in_process:
            self._spawn_procs()
        accept = threading.Thread(target=self._serve, daemon=True)
        accept.start()
        with self._lock:
            self._threads.append(accept)
        producer = threading.Thread(target=self._producer, daemon=True)
        producer.start()
        with self._lock:
            self._threads.append(producer)
        if self.in_process:
            self._spawn_threads()
        try:
            return self._drive(t0)
        finally:
            self.close()

    def _drive(self, t0: float) -> dict:
        deadline = time.monotonic() + self.timeout_s
        # map phase: wait until the chunk stream is drained AND every
        # handed-out chunk has been committed (a dead worker's chunks
        # requeue and re-run through a healthy one)
        while True:
            with self._lock:
                if self._failed:
                    raise RuntimeError(
                        f"distributed ingest failed: {self._failed}")
                done = (self._map_exhausted and not self._pending
                        and not self._requeued)
            if done:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("map phase timed out")
            if not self.in_process and self.worker_procs and \
                    not any(p.is_alive() for p in self.worker_procs):
                with self._lock:
                    stuck = (self._pending or self._requeued
                             or not self._map_exhausted)
                if stuck:
                    raise RuntimeError(
                        "every map worker exited with chunks "
                        "outstanding")
            time.sleep(0.02)
        t_map = time.monotonic()

        # ---- balance: collect per-predicate spilled bytes from every
        # group's sink, then assign predicates size-balanced (greedy,
        # the bulk_shard_outputs policy) — a hash partition alone
        # leaves few-predicate workloads wildly skewed, and the slow
        # group IS the reduce wall-clock. Predicates land where their
        # spill already lives when the balance allows; otherwise the
        # owning reducer streams the spill run to the assignee.
        with self._lock:
            self._want_inventory = True
        while True:
            with self._lock:
                if self._failed:
                    raise RuntimeError(
                        f"distributed ingest failed: {self._failed}")
                if len(self._spill_sizes) == self.groups:
                    break
            if time.monotonic() > deadline:
                raise TimeoutError("spill inventory timed out")
            time.sleep(0.02)
        sizes: dict[str, int] = {}
        home: dict[str, int] = {}
        with self._lock:
            spill_sizes = {g: dict(ss)
                           for g, ss in self._spill_sizes.items()}
        for g, ss in sorted(spill_sizes.items()):
            for p, b in ss.items():
                sizes[p] = sizes.get(p, 0) + b
                home[p] = g
        assign: dict[int, list[str]] = {g: [] for g in
                                        range(1, self.groups + 1)}
        load: dict[int, int] = {g: 0 for g in assign}
        for p in sorted(sizes, key=lambda p: (-sizes[p], p)):
            g = min(sorted(load), key=lambda k: (load[k], k != home[p]))
            assign[g].append(p)
            load[g] += sizes[p]

        # one fixed write_ts for the whole load, allocated AFTER the
        # map so the xid lease high-water is final (ref
        # bulk/loader.go getWriteTimestamp)
        write_ts = self._coord.next_ts()
        with self._lock:
            peers = {str(g): list(a)
                     for g, a in self._reducers.items()}
            for g in assign:
                self._reduce_cmds[g] = {
                    "write_ts": write_ts,
                    "max_ts": self._coord.max_assigned(),
                    "next_uid": self._coord._next_uid,
                    "schema": self.schema,
                    "custom_tokenizers": list(self.custom_tokenizers),
                    "out": os.path.abspath(self.outdir),
                    "assign": sorted(assign[g]),
                    "fetch": {p: home[p] for p in assign[g]
                              if home[p] != g},
                    "peers": peers,
                }
        while True:
            with self._lock:
                if self._failed:
                    raise RuntimeError(
                        f"distributed ingest failed: {self._failed}")
                if len(self._reduce_done) == self.groups:
                    break
                done = set(self._reduce_done)
            # a group is pinned to ONE reducer — no peer can take
            # over its reduce, so a single dead process with its
            # group unreduced must fail the load NOW, not at the
            # phase timeout (_reducer_procs[i] serves group i+1)
            dead = [g for g in range(1, self.groups + 1)
                    if g not in done and self._reducer_procs
                    and not self._reducer_procs[g - 1].is_alive()]
            if dead:
                raise RuntimeError(
                    f"reducer process(es) died with groups "
                    f"{dead} unreduced")
            if time.monotonic() > deadline:
                raise TimeoutError("reduce phase timed out")
            time.sleep(0.02)
        t_reduce = time.monotonic()

        tmap: dict[str, int] = {}
        groups: dict[str, list] = {}
        reduced = 0
        with self._lock:
            reduce_done = {g: dict(st)
                           for g, st in self._reduce_done.items()}
        for g, st in sorted(reduce_done.items()):
            preds = sorted(st.get("preds", ()))
            groups[str(g)] = preds
            reduced += int(st.get("reduced", 0))
            for p in preds:
                tmap[p] = g
        manifest = {
            "groups": groups,
            "tablets": tmap,
            "max_ts": self._coord.max_assigned(),
            "next_uid": self._coord._next_uid,
        }
        os.makedirs(self.outdir, exist_ok=True)
        with open(os.path.join(self.outdir, "manifest.json"),
                  "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.write("\n")
        with self._lock:
            self.stats.update({
                "group_stats": {str(g): {k: v for k, v in st.items()
                                         if k != "preds"}
                                for g, st in
                                sorted(reduce_done.items())},
                "reduced": reduced,
                "map_s": round(t_map - t0, 3),
                "reduce_s": round(t_reduce - t_map, 3),
                "total_s": round(t_reduce - t0, 3),
                "write_ts": write_ts,
            })
            manifest["stats"] = dict(self.stats)
        return manifest

    def close(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for p in self.worker_procs + self._reducer_procs:
            if p.is_alive():
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in self.worker_procs + self._reducer_procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()
                p.join()


def distributed_load(paths, schema: str = "", *, groups: int = 2,
                     workers: int = 2, outdir: str,
                     chunk_bytes: int = _DEFAULT_CHUNK_BYTES,
                     in_process: bool = False,
                     timeout_s: float = 600.0,
                     custom_tokenizers: tuple = ()) -> dict:
    """One-call driver: returns the manifest (with a `stats` section).
    The output directory holds `g<k>/p.snap` bootable group snapshots
    + `manifest.json`, the same contract as `bulk --reduce-shards`."""
    return IngestDriver(paths, schema, groups=groups, workers=workers,
                        outdir=outdir, chunk_bytes=chunk_bytes,
                        in_process=in_process, timeout_s=timeout_s,
                        custom_tokenizers=custom_tokenizers).run()


# --------------------------------------------------------------------------
# map worker
# --------------------------------------------------------------------------


def _dial(addr: tuple[str, int], timeout: float = 30.0
          ) -> socket.socket:
    s = socket.create_connection(addr, timeout=timeout)
    s.settimeout(timeout)
    return s


def _parse_addr(spec: str) -> tuple[str, int]:
    host, port = spec.rsplit(":", 1)
    return host, int(port)


def run_worker(driver_addr: str):
    """Map-worker loop: pull chunks, parse, partition, shuffle. Runs
    as its own process (`python -m dgraph_tpu.ingest.distributed
    worker host:port`) importing only the parse path — no jax."""
    from dgraph_tpu.gql.nquad import parse_rdf

    import numpy as np

    driver = _dial(_parse_addr(driver_addr))
    # wait for every reducer to register before mapping
    while True:
        cfg = _rpc(driver, {"op": "hello"})
        if cfg.get("ready"):
            break
        time.sleep(0.05)
    groups = int(cfg["groups"])
    shuffles = {int(g): _dial(tuple(a))
                for g, a in cfg["shuffle"].items()}
    xid_cache: dict[str, int] = {}

    def resolve(chunk_xids: dict, ref: str) -> int:
        uid = chunk_xids.get(ref)
        if uid is not None:
            return uid
        if not ref.startswith("_:"):
            try:
                return int(ref, 0)
            except ValueError:
                pass
        uid = xid_cache.get(ref)
        if uid is None:
            got = _rpc(driver, {"op": "resolve", "xids": [ref]})
            uid = int(got["uids"][ref])
            xid_cache[ref] = uid
        return uid

    try:
        while True:
            task = _rpc(driver, {"op": "next_chunk"})
            if task.get("done"):
                break
            if task.get("wait"):
                time.sleep(0.01)  # producer hasn't scanned one yet
                continue
            chunk = int(task["chunk"])
            chunk_xids = {k: int(v) for k, v in task["xids"].items()}
            # ---- map: parse + partition by predicate. Values ship
            # COLUMNAR (uid/Val/sparse-lang/sparse-facet columns, file
            # positions implicit in column order): a (src, Posting,
            # idx) tuple per value cost ~20 µs of generic TLV decode
            # on the reduce side — at LDBC shape (value-dominated)
            # that was the reducer's largest line item ----
            parts: dict[str, dict] = {}
            max_uid = 0
            n = 0
            for nq in parse_rdf(task["text"]):
                src = resolve(chunk_xids, nq.subject)
                max_uid = max(max_uid, src)
                part = parts.get(nq.predicate)
                if part is None:
                    part = parts[nq.predicate] = {
                        "src": [], "dst": [], "facets": [],
                        "vsrc": [], "vval": [], "vlang": [],
                        "vfacets": []}
                if nq.object_id:
                    dst = resolve(chunk_xids, nq.object_id)
                    max_uid = max(max_uid, dst)
                    part["src"].append(src)
                    part["dst"].append(dst)
                    if nq.facets:
                        part["facets"].append((src, dst, nq.facets))
                elif nq.object_value is not None:
                    if nq.lang:
                        part["vlang"].append(
                            (len(part["vsrc"]), nq.lang))
                    if nq.facets:
                        part["vfacets"].append(
                            (len(part["vsrc"]), nq.facets))
                    part["vsrc"].append(src)
                    part["vval"].append(nq.object_value)
                n += 1
            # ---- shuffle: transactional per-chunk delivery ----
            touched = sorted({pred_group(p, groups) for p in parts})
            for g in touched:
                _rpc(shuffles[g], {"op": "chunk_begin", "chunk": chunk})
            shuffled = 0
            for pred in sorted(parts):
                part = parts[pred]
                g = pred_group(pred, groups)
                # chaos seam: an armed error here kills this worker
                # mid-shuffle; the chunk requeues and re-streams
                failpoint.fire("ingest.shuffle")
                blob = wire.dumps({
                    "op": "part", "chunk": chunk, "pred": pred,
                    "srcs": np.asarray(part["src"], np.uint64),
                    "dsts": np.asarray(part["dst"], np.uint64),
                    "facets": part["facets"],
                    "vsrc": np.asarray(part["vsrc"], np.uint64),
                    "vval": part["vval"],
                    "vlang": part["vlang"],
                    "vfacets": part["vfacets"]})
                wire.write_frame(shuffles[g], blob)
                wire.loads(wire.read_frame(shuffles[g]))  # ack
                shuffled += len(blob)
            for g in touched:
                _rpc(shuffles[g], {"op": "chunk_commit",
                                   "chunk": chunk})
            _rpc(driver, {"op": "chunk_done", "chunk": chunk,
                          "stats": {"mapped": n,
                                    "shuffled_bytes": shuffled,
                                    "max_uid": max_uid}})
    except failpoint.FailpointError:
        raise  # chaos: die like a SIGKILL would, mid-protocol
    except (EOFError, OSError, wire.WireError):
        pass  # driver gone: load finished or failed without us
    finally:
        for s in shuffles.values():
            s.close()
        driver.close()


# --------------------------------------------------------------------------
# reduce group
# --------------------------------------------------------------------------


class _ShuffleSink:
    """One reduce group's shuffle receiver: stages parts per chunk,
    promotes them to per-predicate spill run files at chunk_commit.
    Re-delivery of a committed chunk is dropped whole — the
    idempotence that makes worker crash-retry byte-exact."""

    def __init__(self, tmpdir: str):
        self.tmpdir = tmpdir
        self.lock = threading.Lock()
        self.staged: dict[int, list[tuple[str, bytes]]] = {}
        self.committed: set[int] = set()
        self.files: dict[str, object] = {}

    def handle(self, req_blob: bytes) -> dict:
        req = wire.loads(req_blob)
        op = req.get("op")
        if op == "chunk_begin":
            with self.lock:
                if int(req["chunk"]) not in self.committed:
                    self.staged[int(req["chunk"])] = []
            return {"ok": True}
        if op == "part":
            with self.lock:
                chunk = int(req["chunk"])
                if chunk not in self.committed:
                    # keep the original frame: the spill file IS the
                    # wire stream, decoded once at reduce time
                    self.staged.setdefault(chunk, []).append(
                        (req["pred"], req_blob))
            return {"ok": True}
        if op == "chunk_commit":
            with self.lock:
                chunk = int(req["chunk"])
                if chunk in self.committed:
                    self.staged.pop(chunk, None)
                    return {"ok": True, "dup": True}
                for pred, blob in self.staged.pop(chunk, []):
                    f = self.files.get(pred)
                    if f is None:
                        path = os.path.join(
                            self.tmpdir,
                            f"spill-{zlib.crc32(pred.encode()):08x}"
                            f"-{len(self.files)}.run")
                        f = self.files[pred] = open(path, "wb")
                    f.write(struct.pack("<I", len(blob)))
                    f.write(blob)
                self.committed.add(chunk)
            return {"ok": True}
        if op == "fetch_spill":
            # reduce-side rebalance: a PEER group assigned one of our
            # staged predicates streams its whole spill run over
            with self.lock:
                f = self.files.get(req["pred"])
                if f is None:
                    return {"ok": True, "data": b""}
                f.flush()
                path = f.name
            with open(path, "rb") as fh:
                return {"ok": True, "data": fh.read()}
        return {"ok": False, "error": f"unknown shuffle op {op!r}"}

    def sizes(self) -> dict[str, int]:
        with self.lock:
            for f in self.files.values():
                f.flush()
            return {p: os.path.getsize(f.name)
                    for p, f in self.files.items()}

    def runs(self) -> dict[str, str]:
        with self.lock:
            for f in self.files.values():
                f.flush()
            return {p: f.name for p, f in self.files.items()}

    def close(self):
        with self.lock:
            for f in self.files.values():
                try:
                    f.close()
                except OSError:
                    pass


def _parse_runs(data: bytes) -> list[dict]:
    out = []
    pos = 0
    while pos + 4 <= len(data):
        (n,) = struct.unpack_from("<I", data, pos)
        pos += 4
        out.append(wire.loads(data[pos:pos + n]))
        pos += n
    return out


def _read_runs(path: str) -> list[dict]:
    with open(path, "rb") as f:
        return _parse_runs(f.read())


def run_reducer(driver_addr: str, group: int):
    """Reduce-group process: receive the shuffle, reduce every owned
    predicate with the shared single-core kernel, write the group's
    bootable snapshot. (`python -m dgraph_tpu.ingest.distributed
    reducer host:port G`)"""
    import numpy as np

    tmpdir = tempfile.mkdtemp(prefix=f"dg-shuffle-g{group}-")
    sink = _ShuffleSink(tmpdir)
    stop = threading.Event()

    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(64)

    def serve_conn(conn):
        try:
            while not stop.is_set():
                blob = wire.read_frame(conn)
                wire.write_frame(conn, wire.dumps(sink.handle(blob)))
        except (EOFError, OSError, wire.WireError):
            pass
        finally:
            conn.close()

    def accept_loop():
        while not stop.is_set():
            try:
                conn, _ = lst.accept()
            except OSError:
                return
            threading.Thread(target=serve_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()

    driver = _dial(_parse_addr(driver_addr))
    try:
        # register FIRST (workers gate their map on every reducer
        # being reachable), THEN pay the heavy engine imports — they
        # overlap the map phase instead of landing on either critical
        # path
        _rpc(driver, {"op": "register_reducer", "group": group,
                      "addr": list(lst.getsockname())})
        from dgraph_tpu.engine.db import GraphDB
        from dgraph_tpu.ingest.bulk import reduce_predicate
        from dgraph_tpu.storage.snapshot import save_snapshot
        from dgraph_tpu.storage.tablet import Posting
        while True:
            got = _rpc(driver, {"op": "reducer_poll", "group": group})
            if got.get("abort"):
                return
            if got.get("inventory"):
                _rpc(driver, {"op": "spill_sizes", "group": group,
                              "sizes": sink.sizes()})
                continue
            if got.get("reduce"):
                cmd = got["reduce"]
                break
            time.sleep(0.05)
        # NOTE: the shuffle listener stays up through the reduce —
        # peer groups fetch_spill rebalanced predicates from it

        t0 = time.monotonic()
        if cmd.get("custom_tokenizers"):
            from dgraph_tpu.models.tokenizer import \
                load_custom_tokenizers
            load_custom_tokenizers(list(cmd["custom_tokenizers"]))
        db = GraphDB(prefer_device=False)
        if cmd["schema"]:
            db.alter(cmd["schema"])
        write_ts = int(cmd["write_ts"])
        reduced = 0
        t_decode = t_reduce = 0.0
        runs = sink.runs()
        fetch = {str(p): int(g)
                 for p, g in cmd.get("fetch", {}).items()}
        peers = {int(g): tuple(a)
                 for g, a in cmd.get("peers", {}).items()}
        assigned = cmd.get("assign")
        if assigned is None:
            assigned = sorted(runs)
        for pred in assigned:
            # chaos seam: delay/fail one predicate's reduce
            failpoint.fire("ingest.reduce")
            td = time.monotonic()
            if pred in runs:
                parts = _read_runs(runs[pred])
            else:
                # rebalanced here: stream the spill from its hash
                # home. Socket faults surface as RuntimeError — the
                # broad except below reports them to the driver; they
                # must never fold into the silent "driver gone" exit
                try:
                    peer = _dial(peers[fetch[pred]])
                    try:
                        got = _rpc(peer, {"op": "fetch_spill",
                                          "pred": pred})
                    finally:
                        peer.close()
                except (EOFError, OSError, wire.WireError) as e:
                    raise RuntimeError(
                        f"fetch_spill {pred!r} from g{fetch[pred]} "
                        f"failed: {type(e).__name__}: {e}") from e
                parts = _parse_runs(got.get("data", b""))
            # canonical order = (chunk, in-part position): reproduces
            # FILE ORDER regardless of worker/commit interleaving,
            # which is what makes a retried shard byte-identical and
            # the value merges match the single-core loader exactly
            parts.sort(key=lambda p: int(p["chunk"]))
            srcs = np.concatenate(
                [p["srcs"] for p in parts]) if parts \
                else np.empty(0, np.uint64)
            dsts = np.concatenate(
                [p["dsts"] for p in parts]) if parts \
                else np.empty(0, np.uint64)
            vals = []
            for p in parts:
                langs = dict(p["vlang"])
                fcs = dict(p["vfacets"])
                for j, (s, v) in enumerate(zip(p["vsrc"].tolist(),
                                               p["vval"])):
                    vals.append((s, Posting(v, langs.get(j, ""),
                                            fcs.get(j, {}))))
            facets = [(fs, fd, fc) for p in parts
                      for fs, fd, fc in p["facets"]]
            tr = time.monotonic()
            t_decode += tr - td
            reduce_predicate(db, pred, srcs, dsts, vals, facets,
                             write_ts)
            t_reduce += time.monotonic() - tr
            reduced += int(len(srcs)) + len(vals)
        db.coordinator.observe_ts(int(cmd["max_ts"]))
        db.coordinator.bump_uids(int(cmd["next_uid"]) - 1)
        gdir = os.path.join(cmd["out"], f"g{group}")
        os.makedirs(gdir, exist_ok=True)
        ts = time.monotonic()
        save_snapshot(db, os.path.join(gdir, "p.snap"))
        _rpc(driver, {"op": "reduce_done", "group": group,
                      "stats": {"preds": list(assigned),
                                "reduced": reduced,
                                "decode_s": round(t_decode, 3),
                                "reduce_s": round(t_reduce, 3),
                                "snap_s": round(
                                    time.monotonic() - ts, 3),
                                "total_s": round(
                                    time.monotonic() - t0, 3)}})
        # LINGER until every group is done: a slower peer may still
        # be fetch_spill-streaming rebalanced predicates from our
        # sink — tearing it down early strands that group
        while True:
            got = _rpc(driver, {"op": "reducer_poll",
                                "group": group})
            if got.get("exit") or got.get("abort"):
                break
            time.sleep(0.05)
    except (EOFError, OSError, wire.WireError):
        pass  # driver gone
    except Exception as e:  # noqa: BLE001 — surface to the driver
        try:
            _rpc(driver, {"op": "failed",
                          "error": f"reducer g{group}: "
                                   f"{type(e).__name__}: {e}"})
        except Exception:  # noqa: BLE001
            pass
        raise
    finally:
        stop.set()
        try:
            lst.close()
        except OSError:
            pass
        sink.close()
        driver.close()
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)


def _main(argv: list[str]) -> int:
    role = argv[0]
    if role == "worker":
        run_worker(argv[1])
        return 0
    if role == "reducer":
        run_reducer(argv[1], int(argv[2]))
        return 0
    log.error("ingest_bad_role", role=role)
    return 2


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
