"""Online live loader: batched transactional ingest with conflict-key
scheduling.

Re-provides dgraph/cmd/live/ semantics: chunked parse, N-quads grouped
into batches (default 1000), batches whose conflict keys overlap an
in-flight batch are held back so they don't abort each other
(live/batch.go:239 conflictKeysForNQuad, :340 addConflictKeys), aborted
batches retry indefinitely.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, Optional

from dgraph_tpu.cluster.coordinator import TxnAborted
from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.gql.nquad import NQuad
from dgraph_tpu.ingest.chunker import chunk_file
from dgraph_tpu.ingest.xidmap import XidMap

DEFAULT_BATCH = 1000
DEFAULT_CONCURRENCY = 4


def _conflict_keys(nqs: list[NQuad]) -> set[int]:
    """Approximation of the per-nquad conflict fingerprint
    (ref live/batch.go:239 conflictKeysForNQuad: pred+subject)."""
    return {zlib.crc32(f"{nq.predicate}\x00{nq.subject}".encode())
            for nq in nqs}


def live_load(db: GraphDB, paths: Iterable[str] = (), *,
              nquads: Optional[Iterator[list[NQuad]]] = None,
              schema: str = "", batch_size: int = DEFAULT_BATCH,
              concurrency: int = DEFAULT_CONCURRENCY,
              xidmap: Optional[XidMap] = None) -> dict:
    """Load into a live GraphDB through real transactions.
    Returns {"nquads": N, "txns": M, "aborts": K}."""
    if schema:
        db.alter(schema)
    xidmap = xidmap or XidMap(db.coordinator)
    stats = {"nquads": 0, "txns": 0, "aborts": 0, "errors": 0}
    stats_lock = threading.Lock()

    # conflict-key scheduler state (ref live/batch.go:340)
    inflight: set[int] = set()
    cv = threading.Condition()

    def batches():
        buf: list[NQuad] = []
        for p in paths:
            for chunk in chunk_file(p):
                buf.extend(chunk)
                while len(buf) >= batch_size:
                    yield buf[:batch_size]
                    buf = buf[batch_size:]
        if nquads is not None:
            for chunk in nquads:
                buf.extend(chunk)
                while len(buf) >= batch_size:
                    yield buf[:batch_size]
                    buf = buf[batch_size:]
        if buf:
            yield buf

    def resolve(nqs: list[NQuad]) -> list[NQuad]:
        out = []
        for nq in nqs:
            sub = nq.subject
            if sub.startswith("_:") or not _is_uid_lit(sub):
                sub = hex(xidmap.assign(sub))
            obj = nq.object_id
            if obj and (obj.startswith("_:") or not _is_uid_lit(obj)):
                obj = hex(xidmap.assign(obj))
            out.append(dataclasses.replace(nq, subject=sub, object_id=obj))
        return out

    def run_batch(nqs: list[NQuad], keys: set[int]):
        ok = False
        try:
            while True:
                txn = db.new_txn()
                try:
                    db._stage(txn, [(nq, False) for nq in nqs])
                    db.commit(txn)
                    ok = True
                    break
                except TxnAborted:
                    db.discard(txn)
                    with stats_lock:
                        stats["aborts"] += 1
                    continue  # infinite retry (ref live loader handleError)
                except Exception as e:  # bad data: drop batch, keep going
                    db.discard(txn)
                    with stats_lock:
                        stats["errors"] += 1
                    print(f"live: dropping batch of {len(nqs)} nquads: {e}",
                          file=sys.stderr)
                    break
        finally:
            with cv:
                inflight.difference_update(keys)
                cv.notify_all()
        if ok:
            with stats_lock:
                stats["txns"] += 1
                stats["nquads"] += len(nqs)

    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        futures = []
        for raw in batches():
            nqs = resolve(raw)
            keys = _conflict_keys(nqs)
            with cv:
                cv.wait_for(lambda: not (keys & inflight))
                inflight.update(keys)
            futures.append(pool.submit(run_batch, nqs, keys))
        for fut in futures:
            fut.result()
    return stats


def _is_uid_lit(ref: str) -> bool:
    try:
        int(ref, 0)
        return True
    except ValueError:
        return False


# ---------------------------------------------------------------------------
# remote mode: load into a RUNNING alpha over HTTP
# ---------------------------------------------------------------------------


def _render_nquad(nq: NQuad, xids: dict) -> str:
    """NQuad -> one RDF statement with blank nodes rewritten to their
    pre-allocated uids (every xid is assigned BEFORE rendering, so the
    output never contains `_:` terms)."""
    from dgraph_tpu.ingest.export import _facet_str, _rdf_value

    def term(t: str) -> str:
        if t.startswith("_:") or not _is_uid_lit(t):
            return f"<{xids[t]:#x}>"
        return f"<{t}>"

    subj = term(nq.subject)
    if nq.star:
        obj = "*"
    elif nq.object_id:
        obj = term(nq.object_id)
    else:
        obj = _rdf_value(nq.object_value)
        if nq.lang:
            obj += f"@{nq.lang}"
    return f"{subj} <{nq.predicate}> {obj}{_facet_str(nq.facets)} ."


class _UidLease:
    """Client-side uid block lease over /assign (ref live/run.go
    allocateUids + zero assign): one HTTP round-trip hands out a block,
    xid->uid assignment is then a local dict insert."""

    def __init__(self, post, block: int = 10_000):
        self._post = post
        self._block = block
        self._next = 0
        self._end = -1
        self._xids: dict[str, int] = {}
        self._lock = threading.Lock()

    def resolve(self, xids_needed: set[str]) -> dict[str, int]:
        with self._lock:
            for x in sorted(xids_needed):
                if x in self._xids:
                    continue
                if self._next > self._end:
                    out = self._post(
                        f"/assign?num={self._block}", b"")
                    self._next = int(out["startId"])
                    self._end = int(out["endId"])
                self._xids[x] = self._next
                self._next += 1
            return self._xids


def remote_live_load(addr: str, paths: Iterable[str] = (), *,
                     schema: str = "", batch_size: int = DEFAULT_BATCH,
                     concurrency: int = DEFAULT_CONCURRENCY,
                     max_retries: int = 50, token: str = "",
                     timeout_s: float = 120.0) -> dict:
    """Stream files into a RUNNING alpha over HTTP — the reference live
    loader's defining mode (dgraph live --alpha, live/run.go:238):
    chunked parse, concurrent batches, abort (409) retry, and uid
    blocks pre-allocated via /assign so blank nodes are concrete uids
    before anything is sent — one xid is one node across batches and
    every batch runs fully parallel. Submission is windowed so a
    multi-GB file never materializes in memory."""
    import json as _json
    import urllib.error
    import urllib.request
    from collections import deque

    base = f"http://{addr}"

    def post(path: str, data: bytes,
             ctype: str = "application/rdf") -> dict:
        headers = {"Content-Type": ctype}
        if token:
            headers["X-Dgraph-AccessToken"] = token
        req = urllib.request.Request(base + path, data=data,
                                     headers=headers)
        return _json.loads(urllib.request.urlopen(
            req, timeout=timeout_s).read())

    if schema:
        post("/alter", schema.encode())

    lease = _UidLease(post)
    stats = {"nquads": 0, "txns": 0, "aborts": 0}
    stats_lock = threading.Lock()

    def send(nqs: list[NQuad]):
        needed = {t for nq in nqs
                  for t in (nq.subject, nq.object_id or "")
                  if t and (t.startswith("_:") or not _is_uid_lit(t))}
        xids = lease.resolve(needed)
        body = "\n".join(_render_nquad(nq, xids) for nq in nqs)
        for attempt in range(max_retries):
            try:
                post("/mutate?commitNow=true", body.encode())
            except urllib.error.HTTPError as e:
                if e.code == 409 and attempt + 1 < max_retries:
                    with stats_lock:
                        stats["aborts"] += 1
                    continue
                raise
            with stats_lock:
                stats["nquads"] += len(nqs)
                stats["txns"] += 1
            return
        raise RuntimeError("batch exhausted retries")

    def batches():
        for path in paths:
            for chunk in chunk_file(path):
                for i in range(0, len(chunk), batch_size):
                    yield chunk[i:i + batch_size]

    # windowed submission: at most ~4x concurrency batches in flight,
    # so parsing streams instead of materializing the whole file
    window = max(concurrency * 4, 4)
    inflight: deque = deque()
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        for b in batches():
            inflight.append(pool.submit(send, b))
            while len(inflight) >= window:
                inflight.popleft().result()
        while inflight:
            inflight.popleft().result()
    return stats
