"""Online live loader: batched transactional ingest with conflict-key
scheduling.

Re-provides dgraph/cmd/live/ semantics: chunked parse, N-quads grouped
into batches (default 1000), batches whose conflict keys overlap an
in-flight batch are held back so they don't abort each other
(live/batch.go:239 conflictKeysForNQuad, :340 addConflictKeys), aborted
batches retry indefinitely.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, Optional

from dgraph_tpu.cluster.coordinator import TxnAborted
from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.gql.nquad import NQuad
from dgraph_tpu.ingest.chunker import chunk_file
from dgraph_tpu.ingest.xidmap import XidMap

DEFAULT_BATCH = 1000
DEFAULT_CONCURRENCY = 4


def _conflict_keys(nqs: list[NQuad]) -> set[int]:
    """Approximation of the per-nquad conflict fingerprint
    (ref live/batch.go:239 conflictKeysForNQuad: pred+subject)."""
    return {zlib.crc32(f"{nq.predicate}\x00{nq.subject}".encode())
            for nq in nqs}


def live_load(db: GraphDB, paths: Iterable[str] = (), *,
              nquads: Optional[Iterator[list[NQuad]]] = None,
              schema: str = "", batch_size: int = DEFAULT_BATCH,
              concurrency: int = DEFAULT_CONCURRENCY,
              xidmap: Optional[XidMap] = None) -> dict:
    """Load into a live GraphDB through real transactions.
    Returns {"nquads": N, "txns": M, "aborts": K}."""
    if schema:
        db.alter(schema)
    xidmap = xidmap or XidMap(db.coordinator)
    stats = {"nquads": 0, "txns": 0, "aborts": 0, "errors": 0}
    stats_lock = threading.Lock()

    # conflict-key scheduler state (ref live/batch.go:340)
    inflight: set[int] = set()
    cv = threading.Condition()

    def batches():
        buf: list[NQuad] = []
        for p in paths:
            for chunk in chunk_file(p):
                buf.extend(chunk)
                while len(buf) >= batch_size:
                    yield buf[:batch_size]
                    buf = buf[batch_size:]
        if nquads is not None:
            for chunk in nquads:
                buf.extend(chunk)
                while len(buf) >= batch_size:
                    yield buf[:batch_size]
                    buf = buf[batch_size:]
        if buf:
            yield buf

    def resolve(nqs: list[NQuad]) -> list[NQuad]:
        out = []
        for nq in nqs:
            sub = nq.subject
            if sub.startswith("_:") or not _is_uid_lit(sub):
                sub = hex(xidmap.assign(sub))
            obj = nq.object_id
            if obj and (obj.startswith("_:") or not _is_uid_lit(obj)):
                obj = hex(xidmap.assign(obj))
            out.append(dataclasses.replace(nq, subject=sub, object_id=obj))
        return out

    def run_batch(nqs: list[NQuad], keys: set[int]):
        ok = False
        try:
            while True:
                txn = db.new_txn()
                try:
                    db._stage(txn, [(nq, False) for nq in nqs])
                    db.commit(txn)
                    ok = True
                    break
                except TxnAborted:
                    db.discard(txn)
                    with stats_lock:
                        stats["aborts"] += 1
                    continue  # infinite retry (ref live loader handleError)
                except Exception as e:  # bad data: drop batch, keep going
                    db.discard(txn)
                    with stats_lock:
                        stats["errors"] += 1
                    print(f"live: dropping batch of {len(nqs)} nquads: {e}",
                          file=sys.stderr)
                    break
        finally:
            with cv:
                inflight.difference_update(keys)
                cv.notify_all()
        if ok:
            with stats_lock:
                stats["txns"] += 1
                stats["nquads"] += len(nqs)

    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        futures = []
        for raw in batches():
            nqs = resolve(raw)
            keys = _conflict_keys(nqs)
            with cv:
                cv.wait_for(lambda: not (keys & inflight))
                inflight.update(keys)
            futures.append(pool.submit(run_batch, nqs, keys))
        for fut in futures:
            fut.result()
    return stats


def _is_uid_lit(ref: str) -> bool:
    try:
        int(ref, 0)
        return True
    except ValueError:
        return False
