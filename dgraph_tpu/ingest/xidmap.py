"""xid → uid assignment with lease blocks.

Re-provides xidmap/xidmap.go:39: external ids (blank nodes, client ids)
map to leased uids; shards keyed by fingerprint reduce lock contention;
optional JSON persistence replaces the reference's Badger backing.
"""

from __future__ import annotations

import json
import os
import threading
import zlib

from dgraph_tpu.cluster.coordinator import Coordinator

NUM_SHARDS = 32        # ref xidmap numShards
LEASE_BLOCK = 10_000   # uids leased per refill (ref xidmap.go block size)


class XidMap:
    def __init__(self, coordinator: Coordinator,
                 persist_path: str | None = None):
        self.coordinator = coordinator
        self.persist_path = persist_path
        self._shards = [dict() for _ in range(NUM_SHARDS)]
        self._locks = [threading.Lock() for _ in range(NUM_SHARDS)]
        self._lease_lock = threading.Lock()
        self._next = 0
        self._last = -1  # empty lease
        if persist_path and os.path.exists(persist_path):
            with open(persist_path) as f:
                loaded = json.load(f)
            for xid, uid in loaded.items():
                self._shards[self._shard(xid)][xid] = uid
                coordinator.bump_uids(uid)

    @staticmethod
    def _shard(xid: str) -> int:
        return zlib.crc32(xid.encode()) % NUM_SHARDS

    def _alloc(self) -> int:
        with self._lease_lock:
            if self._next > self._last:
                self._next, self._last = \
                    self.coordinator.assign_uids(LEASE_BLOCK)
            uid = self._next
            self._next += 1
            return uid

    def assign(self, xid: str) -> int:
        """uid for xid, allocating on first sight
        (ref xidmap.AssignUid, xidmap/xidmap.go:152)."""
        s = self._shard(xid)
        with self._locks[s]:
            uid = self._shards[s].get(xid)
            if uid is None:
                uid = self._alloc()
                self._shards[s][xid] = uid
            return uid

    def lookup(self, xid: str) -> int | None:
        s = self._shard(xid)
        with self._locks[s]:
            return self._shards[s].get(xid)

    def bump_to(self, uid: int):
        """Ensure future allocations exceed `uid`
        (ref xidmap.BumpTo, xidmap/xidmap.go:200)."""
        self.coordinator.bump_uids(uid)
        with self._lease_lock:
            self._next, self._last = 0, -1  # force fresh lease

    def flush(self):
        if not self.persist_path:
            return
        merged = {}
        for s in self._shards:
            merged.update(s)
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, self.persist_path)

    def __len__(self):
        return sum(len(s) for s in self._shards)
