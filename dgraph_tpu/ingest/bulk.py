"""Offline bulk loader: map → shuffle → reduce into tablet base state.

Re-provides dgraph/cmd/bulk/ semantics with a TPU-first reduce:

  reference: mappers emit sorted pb.MapEntry runs per predicate-shard
             (mapper.go:137), reducers k-way-heap-merge them
             (reduce.go:290 postingHeap) into posting lists written as
             Badger SSTs at a fixed writeTs.
  here:      mappers emit flat (src, dst) uid arrays + value posting
             lists per predicate; the reduce is ONE vectorized
             lexsort + boundary-diff per predicate (the device-friendly
             "segmented sort + unique" replacing the heap merge), then
             tablets are constructed directly in base state and the
             index/reverse maps are (re)built.

Everything lands at a single fixed write_ts, exactly like the
reference's fixed writeTs (bulk/loader.go getWriteTimestamp).
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional

import numpy as np

from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.gql.nquad import NQuad
from dgraph_tpu.ingest.chunker import chunk_file
from dgraph_tpu.ingest.xidmap import XidMap
from dgraph_tpu.models.schema import PredicateSchema
from dgraph_tpu.models.types import TypeID, convert
from dgraph_tpu.storage.tablet import Posting, Tablet
from dgraph_tpu.wire import dumps as wire_dumps
from dgraph_tpu.wire import loads as wire_loads

_SPILL_EDGES = 2_000_000  # mapper buffer flush threshold


class _MapShard:
    """Per-predicate mapper accumulator with disk spill."""

    def __init__(self, tmpdir: str, pred: str):
        self.pred = pred
        self.tmpdir = tmpdir
        self.src: list[int] = []
        self.dst: list[int] = []
        self.vals: list[tuple[int, Posting]] = []
        self.facets: list[tuple[int, int, dict]] = []
        self.runs: list[str] = []

    def spill(self):
        if not (self.src or self.vals):
            return
        path = os.path.join(
            self.tmpdir, f"map-{len(self.runs)}-{abs(hash(self.pred))}.run")
        with open(path, "wb") as f:
            f.write(wire_dumps((np.asarray(self.src, np.uint64),
                                np.asarray(self.dst, np.uint64),
                                self.vals, self.facets)))
        self.runs.append(path)
        self.src, self.dst, self.vals, self.facets = [], [], [], []

    def load_all(self):
        """Concatenated (src, dst, vals, facets) over all runs + buffer."""
        srcs = [np.asarray(self.src, np.uint64)]
        dsts = [np.asarray(self.dst, np.uint64)]
        vals = list(self.vals)
        facets = list(self.facets)
        for path in self.runs:
            with open(path, "rb") as f:
                s, d, v, fc = wire_loads(f.read())
            srcs.append(s)
            dsts.append(d)
            vals.extend(v)
            facets.extend(fc)
        return np.concatenate(srcs), np.concatenate(dsts), vals, facets


def bulk_load(paths: Iterable[str] = (), *,
              nquads: Optional[Iterator[list[NQuad]]] = None,
              schema: str = "", db: Optional[GraphDB] = None,
              tmpdir: str | None = None) -> GraphDB:
    """Build a GraphDB offline from RDF/JSON files and/or NQuad batches.
    Ref: dgraph/cmd/bulk/run.go:106 + loader.go mapStage/reduceStage."""
    db = db or GraphDB()
    if schema:
        db.alter(schema)
    own_tmp = tmpdir is None
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="dg-bulk-")
    xidmap = XidMap(db.coordinator)
    shards: dict[str, _MapShard] = {}
    pending_edges = 0

    def shard(pred: str) -> _MapShard:
        s = shards.get(pred)
        if s is None:
            s = _MapShard(tmpdir, pred)
            shards[pred] = s
        return s

    def batches():
        for p in paths:
            yield from chunk_file(p)
        if nquads is not None:
            yield from nquads

    # -- map stage (ref bulk/mapper.go:207 processNQuad) --
    # explicit-uid high-water mark: the coordinator must know the max
    # BEFORE any later blank-node lease is cut (a deferred end-of-batch
    # bump would let a lease block collide with an explicit uid seen
    # earlier in the same batch — review finding), but most statements
    # don't raise the max, so the lock is taken only on a new high
    bumped = 0

    def resolve(ref: str) -> int:
        nonlocal bumped
        uid = _resolve(xidmap, ref)
        if uid > bumped:
            xidmap.coordinator.bump_uids(uid)
            bumped = uid
        return uid

    for batch in batches():
        for nq in batch:
            src = resolve(nq.subject)
            s = shard(nq.predicate)
            if nq.object_id:
                dst = resolve(nq.object_id)
                s.src.append(src)
                s.dst.append(dst)
                if nq.facets:
                    s.facets.append((src, dst, nq.facets))
            elif nq.object_value is not None:
                s.vals.append((src, Posting(nq.object_value, nq.lang,
                                            nq.facets)))
            pending_edges += 1
        if pending_edges >= _SPILL_EDGES:
            for s in shards.values():
                s.spill()
            pending_edges = 0

    # -- reduce stage (ref bulk/reduce.go:50) --
    write_ts = db.coordinator.next_ts()
    for pred, s in shards.items():
        srcs, dsts, vals, facets = s.load_all()
        tab = _tablet_for_bulk(db, pred, srcs, vals)
        if len(srcs):
            # segmented sort + unique: one lexsort replaces the k-way heap
            order = np.lexsort((dsts, srcs))
            srcs, dsts = srcs[order], dsts[order]
            keep = np.ones(len(srcs), bool)
            keep[1:] = (srcs[1:] != srcs[:-1]) | (dsts[1:] != dsts[:-1])
            srcs, dsts = srcs[keep], dsts[keep]
            bounds = np.nonzero(np.r_[True, srcs[1:] != srcs[:-1]])[0]
            ends = np.r_[bounds[1:], len(srcs)]
            for b, e in zip(bounds.tolist(), ends.tolist()):
                src = int(srcs[b])
                old = tab.edges.get(src)
                tab.edges[src] = dsts[b:e].copy() if old is None \
                    else np.union1d(old, dsts[b:e])
            for fsrc, fdst, fc in facets:
                tab.edge_facets[(fsrc, fdst)] = fc
        for src, posting in vals:
            if tab.schema.value_type not in (TypeID.DEFAULT,):
                posting = Posting(
                    convert(posting.value, tab.schema.value_type),
                    posting.lang, posting.facets)
            tab.values[src] = tab._merge_posting(
                tab.values.get(src, []), posting)
        tab.base_ts = write_ts
        tab.rebuild_index()
        tab.rebuild_reverse()
        db.coordinator.should_serve(pred)
        if db.tablet_store is not None:
            # disk-backed load: each predicate offloads to the LSM
            # store as its reduce finishes, so the dataset never has
            # to fit in RAM (ref bulk/reduce.go writing SSTs per
            # predicate shard)
            db.tablets.offload(pred)
    if own_tmp:
        for s in shards.values():
            for r in s.runs:
                os.unlink(r)
        try:
            os.rmdir(tmpdir)
        except OSError:
            pass
    return db


def _resolve(xidmap: XidMap, ref: str) -> int:
    """Explicit uids are NOT bumped here — the map loop tracks the
    batch max and bumps the lease counter once per batch."""
    if ref.startswith("_:"):
        return xidmap.assign(ref)
    try:
        return int(ref, 0)
    except ValueError:
        return xidmap.assign(ref)  # external xid


def _tablet_for_bulk(db: GraphDB, pred: str, srcs, vals) -> Tablet:
    tab = db.tablets.get(pred)
    if tab is not None:
        return tab
    ps = db.schema.get(pred)
    if ps is None:
        if len(srcs) and not vals:
            tid = TypeID.UID
        elif vals:
            tid = vals[0][1].value.tid
            if tid not in (TypeID.INT, TypeID.FLOAT, TypeID.BOOL,
                           TypeID.DATETIME, TypeID.GEO):
                tid = TypeID.DEFAULT
        else:
            tid = TypeID.DEFAULT
        ps = PredicateSchema(pred, value_type=tid)
        db.schema.set_predicate(ps)
    tab = Tablet(pred, ps)
    db.tablets[pred] = tab
    return tab
