"""Offline bulk loader: map → shuffle → reduce into tablet base state.

Re-provides dgraph/cmd/bulk/ semantics with a TPU-first reduce:

  reference: mappers emit sorted pb.MapEntry runs per predicate-shard
             (mapper.go:137), reducers k-way-heap-merge them
             (reduce.go:290 postingHeap) into posting lists written as
             Badger SSTs at a fixed writeTs.
  here:      mappers emit flat (src, dst) uid arrays + value posting
             lists per predicate; the reduce is ONE vectorized
             lexsort + boundary-diff per predicate (the device-friendly
             "segmented sort + unique" replacing the heap merge), then
             tablets are constructed directly in base state and the
             index/reverse maps are (re)built.

Everything lands at a single fixed write_ts, exactly like the
reference's fixed writeTs (bulk/loader.go getWriteTimestamp).
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional

import numpy as np

from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.gql.nquad import (
    _XS_TYPES, _coerce, _unescape, NQuad, parse_facet_text, parse_rdf,
)
from dgraph_tpu.ingest.chunker import _open, chunk_file, detect_format
from dgraph_tpu.ingest.xidmap import XidMap
from dgraph_tpu.models.schema import PredicateSchema
from dgraph_tpu.models.types import TypeID, Val, convert
from dgraph_tpu.storage.tablet import Posting, Tablet
from dgraph_tpu.wire import dumps as wire_dumps
from dgraph_tpu.wire import loads as wire_loads

_SPILL_BYTES = 256 << 20  # mapper buffer flush threshold: approx
# RESIDENT bytes pending across all shards. Byte-based, not
# edge-count: a float32vector posting costs its payload's real size
# (dim * 4 + object overhead), not "one edge" — counting rows
# undercounted vector-heavy inputs by two orders of magnitude and
# blew past the intended memory ceiling. Costs approximate RESIDENT
# python-object sizes (boxed ints, Posting/Val shells), because that
# is what actually fills the mapper's RAM between spills (review
# finding: packed-byte costs undercounted object buffers ~6x).

# python-path edge buffers are LISTS OF INT OBJECTS, not packed
# arrays: two list slots + two boxed ints resident per edge
_EDGE_COST = 72


def _posting_cost(p: Posting) -> int:
    """Approximate RESIDENT bytes of one buffered value posting — the
    spill accountant's unit. The Posting+Val shells cost ~112 B of
    headers/slots; vectors add their exact payload nbytes, strings
    their length; scalars are boxed small objects."""
    v = p.value.value
    if isinstance(v, np.ndarray):
        return 112 + int(v.nbytes)
    if isinstance(v, (str, bytes)):
        return 112 + len(v)
    return 120


class _MapShard:
    """Per-predicate mapper accumulator with disk spill.  Edge uids
    arrive either one at a time (python grammar path: `src`/`dst`
    lists) or as whole per-chunk arrays from the native parser
    (`src_arrs`/`dst_arrs`) — the reduce concatenates both."""

    def __init__(self, tmpdir: str, pred: str):
        self.pred = pred
        self.tmpdir = tmpdir
        self.src: list[int] = []
        self.dst: list[int] = []
        self.src_arrs: list[np.ndarray] = []
        self.dst_arrs: list[np.ndarray] = []
        self.vals: list[tuple[int, Posting]] = []
        self.facets: list[tuple[int, int, dict]] = []
        self.runs: list[str] = []

    def _edge_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        srcs = self.src_arrs + [np.asarray(self.src, np.uint64)]
        dsts = self.dst_arrs + [np.asarray(self.dst, np.uint64)]
        return np.concatenate(srcs), np.concatenate(dsts)

    def spill(self):
        if not (self.src or self.src_arrs or self.vals):
            return
        path = os.path.join(
            self.tmpdir, f"map-{len(self.runs)}-{abs(hash(self.pred))}.run")
        srcs, dsts = self._edge_arrays()
        with open(path, "wb") as f:
            f.write(wire_dumps((srcs, dsts, self.vals, self.facets)))
        self.runs.append(path)
        self.src, self.dst, self.vals, self.facets = [], [], [], []
        self.src_arrs, self.dst_arrs = [], []

    def load_all(self):
        """Concatenated (src, dst, vals, facets) over all runs + buffer."""
        s0, d0 = self._edge_arrays()
        srcs, dsts = [s0], [d0]
        vals = list(self.vals)
        facets = list(self.facets)
        for path in self.runs:
            with open(path, "rb") as f:
                s, d, v, fc = wire_loads(f.read())
            srcs.append(s)
            dsts.append(d)
            vals.extend(v)
            facets.extend(fc)
        return np.concatenate(srcs), np.concatenate(dsts), vals, facets


def bulk_load(paths: Iterable[str] = (), *,
              nquads: Optional[Iterator[list[NQuad]]] = None,
              schema: str = "", db: Optional[GraphDB] = None,
              tmpdir: str | None = None) -> GraphDB:
    """Build a GraphDB offline from RDF/JSON files and/or NQuad batches.
    Ref: dgraph/cmd/bulk/run.go:106 + loader.go mapStage/reduceStage."""
    db = db or GraphDB()
    if schema:
        db.alter(schema)
    # Millions of small Posting/Val objects make cyclic-GC gen2 scans
    # the dominant nonlinearity at the 21M regime (the object graph
    # here is acyclic); the reference tunes GC for bulk the same way
    # (dgraph/main.go GC percent ticker).
    import gc
    gc_was = gc.isenabled()
    gc.disable()
    try:
        return _bulk_load_locked(paths, nquads, db, tmpdir)
    finally:
        if gc_was:
            gc.enable()


def _bulk_load_locked(paths, nquads, db, tmpdir) -> GraphDB:
    own_tmp = tmpdir is None
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="dg-bulk-")
    xidmap = XidMap(db.coordinator)
    shards: dict[str, _MapShard] = {}
    pending_bytes = 0

    def shard(pred: str) -> _MapShard:
        s = shards.get(pred)
        if s is None:
            s = _MapShard(tmpdir, pred)
            shards[pred] = s
        return s

    # -- map stage (ref bulk/mapper.go:207 processNQuad) --
    # explicit-uid high-water mark: the coordinator must know the max
    # BEFORE any later blank-node lease is cut (a deferred end-of-batch
    # bump would let a lease block collide with an explicit uid seen
    # earlier in the same batch — review finding), but most statements
    # don't raise the max, so the lock is taken only on a new high
    bumped = 0

    def resolve(ref: str) -> int:
        uid = _resolve(xidmap, ref)
        bump_to(uid)
        return uid

    def bump_to(uid: int):
        nonlocal bumped
        if uid > bumped:
            xidmap.coordinator.bump_uids(uid)
            bumped = uid

    def add_nq(nq: NQuad):
        # the HOT path: accumulate an approximate packed-byte cost
        # only — the spill threshold check is hoisted to the per-chunk
        # maybe_spill so adds stay one append + one integer bump
        nonlocal pending_bytes
        src = resolve(nq.subject)
        s = shard(nq.predicate)
        if nq.object_id:
            dst = resolve(nq.object_id)
            s.src.append(src)
            s.dst.append(dst)
            if nq.facets:
                s.facets.append((src, dst, nq.facets))
            pending_bytes += _EDGE_COST
        elif nq.object_value is not None:
            p = Posting(nq.object_value, nq.lang, nq.facets)
            s.vals.append((src, p))
            pending_bytes += _posting_cost(p)

    def maybe_spill():
        # batched per map chunk (never per nquad): one threshold
        # check per chunk against the byte-accurate pending total
        nonlocal pending_bytes
        if pending_bytes >= _SPILL_BYTES:
            for s in shards.values():
                s.spill()
            pending_bytes = 0

    from dgraph_tpu import native as _native
    for p in paths:
        fmt = detect_format(p)
        if fmt == "rdf" and _native.available():
            # columnar fast path: the native parser returns whole
            # uid/literal row arrays per chunk; only lines outside its
            # grammar go through the python parser (bit-identical —
            # tested against parse_rdf on the same input)
            for text in _raw_text_chunks(p):
                pending_bytes += _map_native_chunk(
                    text, shard, add_nq, bump_to)
                maybe_spill()
        else:
            for batch in chunk_file(p, fmt):
                for nq in batch:
                    add_nq(nq)
                maybe_spill()
    if nquads is not None:
        for batch in nquads:
            for nq in batch:
                add_nq(nq)
            maybe_spill()

    # -- reduce stage (ref bulk/reduce.go:50) --
    write_ts = db.coordinator.next_ts()
    for pred, s in shards.items():
        srcs, dsts, vals, facets = s.load_all()
        reduce_predicate(db, pred, srcs, dsts, vals,
                         [(fs, fd, fc) for fs, fd, fc in facets],
                         write_ts)
        if db.tablet_store is not None:
            # disk-backed load: each predicate offloads to the LSM
            # store as its reduce finishes, so the dataset never has
            # to fit in RAM (ref bulk/reduce.go writing SSTs per
            # predicate shard)
            db.tablets.offload(pred)
    if own_tmp:
        for s in shards.values():
            for r in s.runs:
                os.unlink(r)
        try:
            os.rmdir(tmpdir)
        except OSError:
            pass
    return db


def reduce_predicate(db: GraphDB, pred: str, srcs, dsts,
                     vals, facets, write_ts: int):
    """One predicate's reduce into base tablet state — the single
    reduce kernel shared by the single-core loader above and the
    per-group distributed reducers (ingest/distributed.py), so the two
    paths produce identical tablets from identical inputs by
    construction. `vals`/`facets` must arrive in FILE ORDER (the
    distributed shuffle tags them with (chunk, idx) and sorts before
    calling here): value-list merge semantics are order-dependent."""
    tab = _tablet_for_bulk(db, pred, srcs, vals)
    if len(srcs):
        # segmented sort + unique: one lexsort replaces the k-way heap
        order = np.lexsort((dsts, srcs))
        srcs, dsts = srcs[order], dsts[order]
        keep = np.ones(len(srcs), bool)
        keep[1:] = (srcs[1:] != srcs[:-1]) | (dsts[1:] != dsts[:-1])
        srcs, dsts = srcs[keep], dsts[keep]
        bounds = np.nonzero(np.r_[True, srcs[1:] != srcs[:-1]])[0]
        ends = np.r_[bounds[1:], len(srcs)]
        for b, e in zip(bounds.tolist(), ends.tolist()):
            src = int(srcs[b])
            old = tab.edges.get(src)
            tab.edges[src] = dsts[b:e].copy() if old is None \
                else np.union1d(old, dsts[b:e])
        for fsrc, fdst, fc in facets:
            tab.edge_facets[(fsrc, fdst)] = fc
    for src, posting in vals:
        if tab.schema.value_type not in (TypeID.DEFAULT,):
            posting = Posting(
                convert(posting.value, tab.schema.value_type),
                posting.lang, posting.facets)
        tab.merge_base_value(src, posting)
    tab.base_ts = write_ts
    tab.rebuild_index()
    tab.rebuild_reverse()
    db.coordinator.should_serve(pred)
    # CDC floor at the bulk write_ts: base state is not change
    # history — a subscriber from offset 0 must re-sync via a
    # snapshot read, never silently skip the bulk data
    db.cdc.reset_floor(pred, write_ts)
    return tab


def bulk_shard_outputs(db: GraphDB, n_groups: int, outdir: str) -> dict:
    """Shard a bulk-loaded store into one bootable snapshot per future
    Alpha group (ref bulk/reduce.go:50 writing out/<i>/p per reduce
    shard + merge_shards.go:34): size-balanced greedy predicate
    partition, `g<k>/p.snap` per group, and a manifest recording the
    tablet map plus the ts/uid watermarks the cluster's Zero must
    honor (alphas push them via the bump_maxes op at boot).

    Every group snapshot carries the FULL schema — the cluster
    replicates schema text everywhere (topology.alter), only tablets
    are sharded."""
    import json

    from dgraph_tpu.storage.snapshot import save_snapshot

    preds = sorted(db.tablets)
    sizes = {p: db.tablets[p].approx_bytes() for p in preds}
    assign: dict[int, list[str]] = {g: [] for g in range(1, n_groups + 1)}
    load: dict[int, int] = {g: 0 for g in assign}
    for p in sorted(preds, key=lambda p: (-sizes[p], p)):
        g = min(sorted(load), key=lambda k: load[k])
        assign[g].append(p)
        load[g] += sizes[p]
    os.makedirs(outdir, exist_ok=True)
    tmap: dict[str, int] = {}
    for g, ps in assign.items():
        sub = GraphDB(prefer_device=False)
        sub.schema = db.schema
        sub.coordinator = db.coordinator
        for p in ps:
            sub.tablets[p] = db.tablets[p]
            tmap[p] = g
        gdir = os.path.join(outdir, f"g{g}")
        os.makedirs(gdir, exist_ok=True)
        save_snapshot(sub, os.path.join(gdir, "p.snap"))
    manifest = {
        "groups": {str(g): sorted(ps) for g, ps in assign.items()},
        "tablets": tmap,
        "max_ts": db.coordinator.max_assigned(),
        "next_uid": db.coordinator._next_uid,
    }
    with open(os.path.join(outdir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    return manifest


_NOID = (1 << 64) - 1  # native parser's "no lang/dtype" sentinel


def _raw_text_chunks(path: str, chunk_bytes: int = 8 << 20):
    """Raw text blocks split at line boundaries (gzip transparent) —
    the native parser's input unit."""
    with _open(path) as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                return
            tail = f.readline()
            yield block + (tail or "")


def _map_native_chunk(text: str, shard, add_nq, bump_to) -> int:
    """One text chunk through native.rdf_parse: edge rows land as
    arrays grouped by predicate, literal rows build Postings directly,
    fallback lines replay through the exact python grammar (ref
    bulk/mapper.go:207 processNQuad, chunker/rdf_parser.go:58).
    Returns the approximate PACKED BYTES buffered (the spill
    accountant's unit; fallback lines self-count through add_nq)."""
    from dgraph_tpu import native

    data = text.encode("utf-8")
    parsed = native.rdf_parse(data)
    if parsed is None:
        for nq in parse_rdf(text):
            add_nq(nq)
        return 0
    e_subj, e_pred, e_dst, e_fs, e_fl = parsed.edges
    (v_subj, v_pred, v_ls, v_ll, v_flags,
     v_lang, v_dtype, v_fs, v_fl) = parsed.vals
    # uid high-water BEFORE any fallback blank-node lease is cut
    hi = 0
    if len(e_subj):
        hi = max(int(e_subj.max()), int(e_dst.max()))
    if len(v_subj):
        hi = max(hi, int(v_subj.max()))
    if hi:
        bump_to(hi)
    preds = parsed.preds
    n = 0
    if len(e_subj):
        order = np.argsort(e_pred, kind="stable")
        ep = e_pred[order]
        bounds = np.nonzero(np.r_[True, ep[1:] != ep[:-1]])[0]
        ends = np.r_[bounds[1:], len(ep)]
        for b, e in zip(bounds.tolist(), ends.tolist()):
            grp = order[b:e]
            s = shard(preds[int(ep[b])])
            s.src_arrs.append(e_subj[grp])
            s.dst_arrs.append(e_dst[grp])
        for i in np.nonzero(e_fl)[0].tolist():
            fc = parse_facet_text(
                data[int(e_fs[i]):int(e_fs[i] + e_fl[i])].decode())
            if fc:  # `( )` parses empty; python's `if nq.facets` skips
                shard(preds[int(e_pred[i])]).facets.append(
                    (int(e_subj[i]), int(e_dst[i]), fc))
        n += int(e_subj.nbytes) + int(e_dst.nbytes)
    if len(v_subj):
        langs, dtypes = parsed.langs, parsed.dtypes
        for subj, pid, ls, ll, fl, lg, dt, fs, flen in zip(
                v_subj.tolist(), v_pred.tolist(), v_ls.tolist(),
                v_ll.tolist(), v_flags.tolist(), v_lang.tolist(),
                v_dtype.tolist(), v_fs.tolist(), v_fl.tolist()):
            sval = data[ls:ls + ll].decode("utf-8")
            if fl & 1:
                sval = _unescape(sval)
            if dt != _NOID:
                dtype = dtypes[dt]
                tid = _XS_TYPES.get(
                    dtype.split("#")[-1] if "#" in dtype else dtype)
                val = _coerce(sval,
                              TypeID.STRING if tid is None else tid)
            else:
                val = Val(TypeID.DEFAULT, sval)
            facets = parse_facet_text(
                data[fs:fs + flen].decode("utf-8")) if flen else {}
            p = Posting(val, langs[lg] if lg != _NOID else "", facets)
            shard(preds[pid]).vals.append((subj, p))
            n += _posting_cost(p)
    fb_s, fb_l = parsed.fallback
    if len(fb_s):
        txt = "\n".join(
            data[int(a):int(a + b)].decode("utf-8")
            for a, b in zip(fb_s.tolist(), fb_l.tolist()))
        for nq in parse_rdf(txt):
            add_nq(nq)
    return n


def _resolve(xidmap: XidMap, ref: str) -> int:
    """Explicit uids are NOT bumped here — the map loop tracks the
    batch max and bumps the lease counter once per batch."""
    if ref.startswith("_:"):
        return xidmap.assign(ref)
    try:
        return int(ref, 0)
    except ValueError:
        return xidmap.assign(ref)  # external xid


def _tablet_for_bulk(db: GraphDB, pred: str, srcs, vals) -> Tablet:
    tab = db.tablets.get(pred)
    if tab is not None:
        return tab
    ps = db.schema.get(pred)
    if ps is None:
        if len(srcs) and not vals:
            tid = TypeID.UID
        elif vals:
            tid = vals[0][1].value.tid
            if tid not in (TypeID.INT, TypeID.FLOAT, TypeID.BOOL,
                           TypeID.DATETIME, TypeID.GEO,
                           TypeID.FLOAT32VECTOR):
                tid = TypeID.DEFAULT
        else:
            tid = TypeID.DEFAULT
        ps = PredicateSchema(pred, value_type=tid)
        db.schema.set_predicate(ps)
    tab = Tablet(pred, ps)
    db.tablets[pred] = tab
    return tab
