"""Export a live store to RDF or JSON + schema text.

Re-provides worker/export.go:376: full-database egress at a read
timestamp, RDF N-Quads with language tags, typed literals and facets,
or JSON objects; plus the schema document. The output round-trips
through the bulk/live loaders (the reference's export→bulk cycle).
"""

from __future__ import annotations

import base64
import json
from typing import Iterator

from dgraph_tpu.models.types import TypeID, Val, to_json_value


def _rdf_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n").replace("\r", "\\r").replace("\t", "\\t")


_XS = {TypeID.INT: "xs:int", TypeID.FLOAT: "xs:float",
       TypeID.BOOL: "xs:boolean", TypeID.DATETIME: "xs:dateTime",
       TypeID.GEO: "geo:geojson", TypeID.PASSWORD: "xs:password",
       TypeID.BINARY: "xs:base64Binary"}


def _rdf_value(v: Val) -> str:
    if v.tid == TypeID.DATETIME:
        from dgraph_tpu.models.types import iso8601
        raw = iso8601(v.value)
    elif v.tid == TypeID.GEO:
        raw = json.dumps(v.value)
    elif v.tid == TypeID.BOOL:
        raw = "true" if v.value else "false"
    elif v.tid == TypeID.BINARY:
        raw = base64.b64encode(v.value).decode()
    else:
        raw = str(v.value)
    lit = f'"{_rdf_escape(raw)}"'
    xs = _XS.get(v.tid)
    return f"{lit}^^<{xs}>" if xs else lit


def _facet_str(facets: dict) -> str:
    if not facets:
        return ""
    parts = []
    for k, v in sorted(facets.items()):
        if isinstance(v, Val):
            if v.tid == TypeID.STRING:
                parts.append(f'{k}="{_rdf_escape(str(v.value))}"')
            elif v.tid == TypeID.DATETIME:
                parts.append(f'{k}={v.value.isoformat()}')
            elif v.tid == TypeID.BOOL:
                parts.append(f"{k}={'true' if v.value else 'false'}")
            else:
                parts.append(f"{k}={v.value}")
        else:
            parts.append(f"{k}={v}")
    return " (" + ", ".join(parts) + ")"


def export_rdf(db, read_ts: int | None = None) -> Iterator[str]:
    """Yield N-Quad lines for every posting visible at read_ts."""
    read_ts = read_ts if read_ts is not None \
        else db.coordinator.max_assigned()
    for pred in sorted(db.tablets):
        tab = db.tablets[pred]
        if tab.is_uid:
            for src in sorted(tab.src_uids(read_ts).tolist()):
                for dst in tab.get_dst_uids(src, read_ts).tolist():
                    fc = tab.get_facets(src, int(dst), read_ts)
                    yield (f"<{hex(src)}> <{pred}> <{hex(int(dst))}>"
                           f"{_facet_str(fc)} .")
        else:
            for src in sorted(tab.src_uids(read_ts).tolist()):
                for p in tab.get_postings(src, read_ts):
                    lang = f"@{p.lang}" if p.lang else ""
                    val = _rdf_value(p.value)
                    if lang and val.startswith('"') and "^^" not in val:
                        yield (f"<{hex(src)}> <{pred}> {val}{lang}"
                               f"{_facet_str(p.facets)} .")
                    else:
                        yield (f"<{hex(src)}> <{pred}> {val}"
                               f"{_facet_str(p.facets)} .")


def export_json(db, read_ts: int | None = None) -> list[dict]:
    """All nodes as JSON objects keyed by uid (ref export.go JSON mode)."""
    read_ts = read_ts if read_ts is not None \
        else db.coordinator.max_assigned()
    nodes: dict[int, dict] = {}

    def node(uid: int) -> dict:
        n = nodes.get(uid)
        if n is None:
            n = {"uid": hex(uid)}
            nodes[uid] = n
        return n

    for pred in sorted(db.tablets):
        tab = db.tablets[pred]
        if tab.is_uid:
            for src in tab.src_uids(read_ts).tolist():
                node(src)[pred] = [
                    {"uid": hex(int(d))}
                    for d in tab.get_dst_uids(src, read_ts).tolist()]
        else:
            for src in tab.src_uids(read_ts).tolist():
                ps = tab.get_postings(src, read_ts)
                if tab.schema.list_:
                    node(src)[pred] = [to_json_value(p.value) for p in ps]
                else:
                    for p in ps:
                        key = f"{pred}@{p.lang}" if p.lang else pred
                        node(src)[key] = to_json_value(p.value)
    return [nodes[u] for u in sorted(nodes)]


def export_schema(db) -> str:
    """Schema document re-parseable by alter()
    (ref worker/export.go schema output)."""
    return db.schema.describe_all()
