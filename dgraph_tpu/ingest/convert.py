"""Data converters: SQL -> RDF (migrate) and GeoJSON -> RDF (conv).

The reference ships `dgraph migrate` (dgraph/cmd/migrate: walks a SQL
database's schema, turns tables into types, rows into nodes, foreign
keys into uid edges, and emits .rdf + .schema files) and `dgraph conv`
(dgraph/cmd/conv: geo files into RDF). Same tools here, with sqlite as
the SQL source (stdlib; the reference targets MySQL — the mapping
logic is identical, the driver differs).
"""

from __future__ import annotations

import json
import re
import sqlite3
from typing import TextIO

from dgraph_tpu.ingest.export import _rdf_escape

_LABEL_BAD = re.compile(r"[^0-9A-Za-z_.-]")
_PRED_BAD = re.compile(r"[^0-9A-Za-z_.]")


def _label(s: str) -> str:
    """Blank-node label component: only [A-Za-z0-9_.-] survive; other
    bytes hex-encode so distinct keys stay distinct ('John Smith' and
    'John_Smith' must not collide)."""
    return _LABEL_BAD.sub(lambda m: f"_x{ord(m.group(0)):02x}", str(s))


def _pred(s: str) -> str:
    """Predicate name: word chars + dots (GeoJSON property names in
    the wild contain spaces and punctuation)."""
    return _PRED_BAD.sub("_", str(s)) or "_"


# ---------------------------------------------------------------------------
# migrate: sqlite -> RDF + schema  (ref dgraph/cmd/migrate/run.go)
# ---------------------------------------------------------------------------

_SQL_TO_DGRAPH = {
    "INTEGER": "int", "INT": "int", "BIGINT": "int", "SMALLINT": "int",
    "REAL": "float", "FLOAT": "float", "DOUBLE": "float",
    "NUMERIC": "float", "DECIMAL": "float",
    "BOOLEAN": "bool", "BOOL": "bool",
    "DATE": "datetime", "DATETIME": "datetime", "TIMESTAMP": "datetime",
}


def _dgraph_type(sql_type: str) -> str:
    base = (sql_type or "").split("(")[0].strip().upper()
    return _SQL_TO_DGRAPH.get(base, "string")


def _sql_ident(name: str) -> str:
    return '"' + name.replace('"', '""') + '"'


def migrate_sqlite(db_path: str, rdf_out: TextIO, schema_out: TextIO,
                   separator: str = ".") -> dict:
    """Walk a sqlite database: every table row becomes a node typed by
    the table, every column a `table.column` predicate, every foreign
    key a uid edge to the referenced row's blank node (ref
    migrate/table_guide.go blank-node naming _:<table>_<pk>).

    A FK edge is only emitted when the referenced columns ARE the
    referenced table's primary key (in order) — that's the only case
    where the target blank-node label is derivable; anything else
    (rowid refs without an INTEGER PRIMARY KEY, FKs onto non-pk
    columns) is counted in stats["skipped_fks"] instead of emitting
    silently dangling edges."""
    conn = sqlite3.connect(db_path)
    conn.row_factory = sqlite3.Row
    tables = [r["name"] for r in conn.execute(
        "SELECT name FROM sqlite_master WHERE type='table' "
        "AND name NOT LIKE 'sqlite_%' ORDER BY name")]
    stats = {"tables": len(tables), "rows": 0, "edges": 0,
             "skipped_fks": 0}
    preds: dict[str, str] = {}
    types: dict[str, list[str]] = {}

    def pk_of(table: str) -> list[str]:
        cols = list(conn.execute(f"PRAGMA table_info({_sql_ident(table)})"))
        pk = sorted((c["pk"], c["name"]) for c in cols if c["pk"])
        return [name for _, name in pk] or [c["name"] for c in cols]

    for table in tables:
        cols = list(conn.execute(f"PRAGMA table_info({_sql_ident(table)})"))
        # composite-aware FK map: fk id -> (ref table, [(from, to)...])
        fk_groups: dict[int, tuple[str, list]] = {}
        for r in conn.execute(
                f"PRAGMA foreign_key_list({_sql_ident(table)})"):
            fk_groups.setdefault(r["id"], (r["table"], []))[1].append(
                (r["from"], r["to"]))
        # resolvable FK: referenced cols == referenced table's pk order
        fk_cols: dict[str, tuple[str, int]] = {}  # from-col -> (ref, id)
        fk_emittable: dict[int, list[str]] = {}
        for fid, (ref_table, pairs) in fk_groups.items():
            ref_pk = pk_of(ref_table)
            tos = [t if t is not None else rp
                   for (_, t), rp in zip(pairs, ref_pk)] \
                if len(pairs) == len(ref_pk) else None
            if tos == ref_pk:
                fk_emittable[fid] = [f for f, _ in pairs]
            for f, _ in pairs:
                fk_cols[f] = (ref_table, fid)

        pk_cols = pk_of(table)
        type_preds = []
        for c in cols:
            pred = f"{_pred(table)}{separator}{_pred(c['name'])}"
            if c["name"] in fk_cols:
                preds[pred] = "[uid] @reverse"
            else:
                preds[pred] = _dgraph_type(c["type"])
            type_preds.append(pred)
        types[_pred(table)] = type_preds

        for row in conn.execute(f"SELECT * FROM {_sql_ident(table)}"):
            pk = "_".join(_label(row[c]) for c in pk_cols)
            subj = f"_:{_label(table)}_{pk}"
            rdf_out.write(
                f'{subj} <dgraph.type> "{_rdf_escape(table)}" .\n')
            stats["rows"] += 1
            emitted_fks: set[int] = set()
            for c in cols:
                name = c["name"]
                v = row[name]
                if v is None:
                    continue
                pred = f"{_pred(table)}{separator}{_pred(name)}"
                if name in fk_cols:
                    ref_table, fid = fk_cols[name]
                    if fid not in fk_emittable or fid in emitted_fks:
                        if fid not in fk_emittable:
                            stats["skipped_fks"] += 1
                        continue
                    emitted_fks.add(fid)
                    parts = [row[f] for f in fk_emittable[fid]]
                    if any(p is None for p in parts):
                        continue
                    target = "_".join(_label(p) for p in parts)
                    rdf_out.write(
                        f"{subj} <{pred}> _:{_label(ref_table)}_{target}"
                        " .\n")
                    stats["edges"] += 1
                elif isinstance(v, bytes):
                    continue  # blobs don't survive RDF text form
                else:
                    rdf_out.write(
                        f'{subj} <{pred}> "{_rdf_escape(str(v))}" .\n')

    for pred, ptype in sorted(preds.items()):
        # every scalar column gets a lookup index: migrated data is
        # queried by former SQL key columns (root eq/ineq needs an
        # index, like the reference server)
        idx = {"string": " @index(exact)", "int": " @index(int)",
               "float": " @index(float)", "bool": " @index(bool)",
               "datetime": " @index(datetime)"}.get(ptype, "")
        schema_out.write(f"{pred}: {ptype}{idx} .\n")
    for tname, tpreds in sorted(types.items()):
        schema_out.write(f"type {tname} {{\n")
        for p in tpreds:
            schema_out.write(f"  {p}\n")
        schema_out.write("}\n")
    conn.close()
    return stats


# ---------------------------------------------------------------------------
# conv: GeoJSON -> RDF  (ref dgraph/cmd/conv/conv.go)
# ---------------------------------------------------------------------------


def convert_geojson(geojson_in: TextIO, rdf_out: TextIO,
                    geopred: str = "loc") -> dict:
    """FeatureCollection -> one node per feature: geometry under
    `geopred` (geojson literal) plus every scalar property (property
    names sanitized to legal predicate form)."""
    doc = json.load(geojson_in)
    feats = doc.get("features", [doc] if doc.get("geometry") else [])
    n = 0
    for i, feat in enumerate(feats):
        geom = feat.get("geometry")
        if not geom:
            continue
        subj = f"_:geo_{i}"
        gq = _rdf_escape(json.dumps(geom, separators=(",", ":")))
        rdf_out.write(
            f'{subj} <{_pred(geopred)}> "{gq}"^^<geo:geojson> .\n')
        for k, v in (feat.get("properties") or {}).items():
            if v is None or isinstance(v, (dict, list)):
                continue
            rdf_out.write(
                f'{subj} <{_pred(k)}> "{_rdf_escape(str(v))}" .\n')
        n += 1
    return {"features": n}
