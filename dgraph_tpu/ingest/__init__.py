"""Ingest/egress: streaming chunkers, xid→uid assignment, offline bulk
map-reduce loader, online live loader, and RDF/JSON export.

Ref: chunker/ (streaming parse), xidmap/ (xid assignment),
dgraph/cmd/bulk/ (offline loader), dgraph/cmd/live/ (online loader),
worker/export.go (export).
"""

from dgraph_tpu.ingest.chunker import Chunker, chunk_file, detect_format
from dgraph_tpu.ingest.xidmap import XidMap
from dgraph_tpu.ingest.bulk import bulk_load
from dgraph_tpu.ingest.live import live_load
from dgraph_tpu.ingest.export import export_json, export_rdf, export_schema

__all__ = ["Chunker", "chunk_file", "detect_format", "XidMap",
           "bulk_load", "live_load", "export_json", "export_rdf",
           "export_schema"]
