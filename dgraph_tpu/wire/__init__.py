"""Versioned wire format for durable records and network frames.

The reference's stable encoding is protobuf (protos/pb.proto:469-501
Posting/PostingList/Proposal et al); every durable or networked payload
goes through it, so old WALs replay and mixed-version nodes interoperate.
This package is the analogue: a compact, self-describing, versioned
binary encoding (tag + varint TLV) with first-class records for the
engine's EdgeOp/Posting/Val, Raft's Entry/Msg, and numpy arrays.
Pickle — self-compatible only, code-layout-fragile — is no longer used
for anything durable or replicated.

Layout: one version byte, then a tagged value tree. Integers are
zigzag varints; arrays carry dtype + shape + raw little-endian bytes.
"""

from dgraph_tpu.wire.codec import (  # noqa: F401
    WIRE_VERSION, WireError, decode, dumps, encode, loads, loads_compat,
    read_frame, write_frame,
)
