"""Tagged binary encoding (the pb.proto role: a stable record format).

Every value is `tag byte + payload`. Varints are LEB128; signed ints
zigzag. Strings are UTF-8, arrays raw little-endian. Dataclass records
(Val, Posting, EdgeOp, raft Entry/Msg) get their own tags with
positional fields — adding a field later means a new tag, old tags stay
decodable (the protobuf discipline, without the codegen).

Ref: protos/pb.proto Posting (:469), DirectedEdge, Proposal; codec
discipline: raftwal/storage.go encodes entries through proto too.
"""

from __future__ import annotations

import datetime as _dt
import struct
from typing import Any, BinaryIO

import numpy as np

WIRE_VERSION = 1


class WireError(ValueError):
    pass


# -- tags -------------------------------------------------------------------

T_NONE = 0x00
T_TRUE = 0x01
T_FALSE = 0x02
T_INT = 0x03
T_FLOAT = 0x04
T_STR = 0x05
T_BYTES = 0x06
T_LIST = 0x07
T_TUPLE = 0x08
T_DICT = 0x09
T_NDARRAY = 0x0A
T_DATETIME = 0x0B
T_DATE = 0x0C
T_VAL = 0x10
T_POSTING = 0x11
T_EDGEOP = 0x12
T_ENTRY = 0x13
T_MSG = 0x14


def _uvarint(out: bytearray, n: int):
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if -(1 << 63) <= n < (1 << 63) \
        else _big_zigzag(n)


def _big_zigzag(n: int) -> int:
    # arbitrary-precision fallback (uids are < 2^64; this is belt &
    # braces for e.g. huge math() artifacts that land in a Val).
    # Decode bounds varints at 126 shift bits — reject anything the
    # decoder could not read back, never write-then-brick.
    u = (n << 1) if n >= 0 else ((-n) << 1) - 1
    if u.bit_length() > 126:
        raise WireError(f"int too large to encode ({n.bit_length()} bits)")
    return u


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise WireError("truncated payload")
        self.pos += n
        return b

    def byte(self) -> int:
        if self.pos >= len(self.buf):
            raise WireError("truncated payload")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def uvarint(self) -> int:
        shift = 0
        n = 0
        while True:
            b = self.byte()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 126:
                raise WireError("varint too long")


# -- encode -----------------------------------------------------------------


def encode(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(T_NONE)
    elif obj is True:
        out.append(T_TRUE)
    elif obj is False:
        out.append(T_FALSE)
    elif isinstance(obj, (int, np.integer)):
        out.append(T_INT)
        _uvarint(out, _zigzag(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(T_FLOAT)
        out += struct.pack("<d", float(obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(T_STR)
        _uvarint(out, len(b))
        out += b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        out.append(T_BYTES)
        _uvarint(out, len(obj))
        out += obj
    elif isinstance(obj, list):
        out.append(T_LIST)
        _uvarint(out, len(obj))
        for x in obj:
            encode(x, out)
    elif isinstance(obj, tuple):
        out.append(T_TUPLE)
        _uvarint(out, len(obj))
        for x in obj:
            encode(x, out)
    elif isinstance(obj, dict):
        out.append(T_DICT)
        _uvarint(out, len(obj))
        for k, v in obj.items():
            encode(k, out)
            encode(v, out)
    elif isinstance(obj, np.ndarray):
        out.append(T_NDARRAY)
        dt = obj.dtype.str  # e.g. '<u8' — endian-explicit
        db = dt.encode()
        _uvarint(out, len(db))
        out += db
        _uvarint(out, obj.ndim)
        for s in obj.shape:
            _uvarint(out, s)
        raw = np.ascontiguousarray(obj).tobytes()
        _uvarint(out, len(raw))
        out += raw
    elif isinstance(obj, _dt.datetime):
        out.append(T_DATETIME)
        s = obj.isoformat()
        b = s.encode()
        _uvarint(out, len(b))
        out += b
    elif isinstance(obj, _dt.date):
        out.append(T_DATE)
        b = obj.isoformat().encode()
        _uvarint(out, len(b))
        out += b
    else:
        enc = _RECORD_ENC.get(type(obj).__name__)
        if enc is None:
            raise WireError(
                f"wire: unencodable type {type(obj).__name__}")
        enc(obj, out)


def _enc_val(v, out: bytearray):
    out.append(T_VAL)
    _uvarint(out, int(v.tid))
    encode(v.value, out)


def _enc_posting(p, out: bytearray):
    out.append(T_POSTING)
    _enc_val(p.value, out)
    encode(p.lang, out)
    encode(p.facets, out)


def _enc_edgeop(e, out: bytearray):
    out.append(T_EDGEOP)
    encode(e.op, out)
    _uvarint(out, _zigzag(e.src))
    _uvarint(out, _zigzag(e.dst))
    encode(e.posting, out)
    encode(e.facets, out)


def _enc_entry(e, out: bytearray):
    out.append(T_ENTRY)
    _uvarint(out, e.term)
    _uvarint(out, e.index)
    encode(e.data, out)


_MSG_FIELDS = ("type", "frm", "to", "term", "last_log_index",
               "last_log_term", "granted", "prev_index", "prev_term",
               "entries", "commit", "success", "match_index",
               "reject_hint", "snap_index", "snap_term", "snap_data")


def _enc_msg(m, out: bytearray):
    out.append(T_MSG)
    for f in _MSG_FIELDS:
        encode(getattr(m, f), out)


_RECORD_ENC = {
    "Val": _enc_val,
    "Posting": _enc_posting,
    "EdgeOp": _enc_edgeop,
    "Entry": _enc_entry,
    "Msg": _enc_msg,
}


# -- decode -----------------------------------------------------------------


def decode(r: _Reader) -> Any:
    tag = r.byte()
    if tag == T_NONE:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _unzigzag(r.uvarint())
    if tag == T_FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == T_STR:
        return r.take(r.uvarint()).decode("utf-8")
    if tag == T_BYTES:
        return bytes(r.take(r.uvarint()))
    if tag == T_LIST:
        return [decode(r) for _ in range(r.uvarint())]
    if tag == T_TUPLE:
        return tuple(decode(r) for _ in range(r.uvarint()))
    if tag == T_DICT:
        return {decode(r): decode(r) for _ in range(r.uvarint())}
    if tag == T_NDARRAY:
        dt = np.dtype(r.take(r.uvarint()).decode())
        shape = tuple(r.uvarint() for _ in range(r.uvarint()))
        raw = r.take(r.uvarint())
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == T_DATETIME:
        return _dt.datetime.fromisoformat(r.take(r.uvarint()).decode())
    if tag == T_DATE:
        return _dt.date.fromisoformat(r.take(r.uvarint()).decode())
    if tag == T_VAL:
        from dgraph_tpu.models.types import TypeID, Val
        tid = TypeID(r.uvarint())
        return Val(tid, decode(r))
    if tag == T_POSTING:
        from dgraph_tpu.storage.tablet import Posting
        val = decode(r)
        return Posting(val, decode(r), decode(r))
    if tag == T_EDGEOP:
        from dgraph_tpu.storage.tablet import EdgeOp
        op = decode(r)
        src = _unzigzag(r.uvarint())
        dst = _unzigzag(r.uvarint())
        return EdgeOp(op, src, dst, decode(r), decode(r))
    if tag == T_ENTRY:
        from dgraph_tpu.cluster.raft import Entry
        term = r.uvarint()
        index = r.uvarint()
        return Entry(term, index, decode(r))
    if tag == T_MSG:
        from dgraph_tpu.cluster.raft import Msg
        kw = {f: decode(r) for f in _MSG_FIELDS}
        return Msg(**kw)
    raise WireError(f"wire: unknown tag {tag:#x}")


# -- public API -------------------------------------------------------------


def dumps(obj: Any) -> bytes:
    out = bytearray([WIRE_VERSION])
    encode(obj, out)
    return bytes(out)


def loads(data: bytes) -> Any:
    if not data:
        raise WireError("empty payload")
    if data[0] != WIRE_VERSION:
        raise WireError(f"wire version {data[0]} unsupported")
    r = _Reader(data, 1)
    obj = decode(r)
    return obj


def loads_compat(data: bytes) -> Any:
    """loads() with a pickle fallback for payloads written before the
    wire format existed (pickle's PROTO opcode is 0x80, which can never
    be a wire version byte). Use for durable artifacts that may predate
    the migration — raft snapshots, engine snapshot blobs."""
    if data[:1] == b"\x80":
        import pickle
        return pickle.loads(data)
    return loads(data)


# -- framing (TCP transport / file records) ---------------------------------

_FRAME_HDR = struct.Struct("<I")
MAX_FRAME = 1 << 30


def write_frame(sock_or_file, payload: bytes) -> None:
    """Length-prefixed frame; works on sockets (sendall) and files."""
    hdr = _FRAME_HDR.pack(len(payload))
    if hasattr(sock_or_file, "sendall"):
        sock_or_file.sendall(hdr + payload)
    else:
        sock_or_file.write(hdr + payload)


def _read_exact(src, n: int) -> bytes:
    if hasattr(src, "recv"):
        parts = []
        got = 0
        while got < n:
            b = src.recv(n - got)
            if not b:
                raise EOFError("peer closed")
            parts.append(b)
            got += len(b)
        return b"".join(parts)
    b = src.read(n)
    if len(b) != n:
        raise EOFError("short read")
    return b


def read_frame(src: BinaryIO) -> bytes:
    (n,) = _FRAME_HDR.unpack(_read_exact(src, _FRAME_HDR.size))
    if n > MAX_FRAME:
        raise WireError(f"frame too large ({n} bytes)")
    return _read_exact(src, n)
