"""Mutation input parsing: RDF N-Quads and JSON.

Re-provides the reference's chunker package behavior (chunker/rdf_parser.go:58
ParseRDFs, chunker/json_parser.go) — triples with optional facets, language
tags, type hints (`"3"^^<xs:int>`), blank nodes, star deletion — as a fresh
regex/recursive parser.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any

from dgraph_tpu.gql.lexer import GQLError
from dgraph_tpu.models.types import TypeID, Val


@dataclass
class NQuad:
    """One parsed triple. Ref pb.NQuad / api.NQuad."""

    subject: str              # uid literal "0x1", blank "_:x", xid, or "uid(v)"
    predicate: str
    object_id: str = ""       # set for uid objects (may be "uid(v)")
    object_value: Val | None = None
    lang: str = ""
    facets: dict[str, Val] = field(default_factory=dict)
    star: bool = False        # object was *  (delete-all)
    val_var: str = ""         # object was val(v) — upsert value substitution


_XS_TYPES = {
    "xs:int": TypeID.INT, "xs:integer": TypeID.INT,
    "xs:positiveInteger": TypeID.INT,
    "xs:float": TypeID.FLOAT, "xs:double": TypeID.FLOAT,
    "xs:boolean": TypeID.BOOL, "xs:bool": TypeID.BOOL,
    "xs:dateTime": TypeID.DATETIME, "xs:date": TypeID.DATETIME,
    "xs:string": TypeID.STRING,
    "geo:geojson": TypeID.GEO,
    "xs:password": TypeID.PASSWORD,
    "xs:base64Binary": TypeID.BINARY,
    # modern Dgraph's vfloat literal: "[0.1, 0.2]"^^<xs:float32vector>
    "xs:float32vector": TypeID.FLOAT32VECTOR,
    "float32vector": TypeID.FLOAT32VECTOR,
}


def _coerce(raw: str, tid: TypeID) -> Val:
    if tid == TypeID.INT:
        return Val(tid, int(raw))
    if tid == TypeID.FLOAT:
        return Val(tid, float(raw))
    if tid == TypeID.BOOL:
        return Val(tid, raw.lower() == "true")
    if tid == TypeID.DATETIME:
        from dgraph_tpu.models.types import parse_datetime

        return Val(tid, parse_datetime(raw))
    if tid == TypeID.GEO:
        return Val(tid, json.loads(raw))
    if tid == TypeID.BINARY:
        import base64

        return Val(tid, base64.b64decode(raw))
    if tid == TypeID.FLOAT32VECTOR:
        from dgraph_tpu.models.types import parse_vector

        return Val(tid, parse_vector(raw))
    return Val(tid, raw)


_TERM = re.compile(
    r"""\s*(?:
      (?P<iri><[^>]*>)
    | (?P<blank>_:[\w.\-]+)
    | (?P<star>\*)
    | (?P<literal>"(?:\\.|[^"\\])*")
        (?:@(?P<lang>[\w\-]+)|\^\^<(?P<dtype>[^>]+)>)?
    | (?P<func>(?:uid|val)\(\s*[\w.\-]+\s*\))
    | (?P<word>[\w.\-~/]+)
    )""",
    re.VERBOSE,
)

_UNESC = re.compile(r"\\(.)")
_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "'": "'"}


def _unescape(s: str) -> str:
    return _UNESC.sub(lambda m: _ESCAPES.get(m.group(1), m.group(1)), s)


# Fast path: one regex for the overwhelmingly common one-statement-
# per-line shapes (`<s> <p> <o> .`, `<s> <p> "lit"[@lang|^^<dt>] .`,
# blank nodes either side) — one match() instead of three cursor steps
# with per-group dispatch. Anything else (facets, uid()/val() terms,
# graph labels, multiple statements per line, `*`) falls back to the
# full grammar below. Bulk-load profiles are parse-bound without this.
_FAST = re.compile(
    r'(?:<(?P<si>[^>]*)>|(?P<sb>_:[\w.\-]+))'
    r'\s+(?:<(?P<pi>[^>]+)>|(?P<pw>[\w.\-~/]+))'
    r'\s+(?:<(?P<oi>[^>]*)>|(?P<ob>_:[\w.\-]+)|'
    r'"(?P<lit>(?:\\.|[^"\\])*)"'
    r'(?:@(?P<lang>[\w\-]+)|\^\^<(?P<dt>[^>]+)>)?)'
    r'\s*\.\s*$')


def _fast_nquad(m) -> NQuad:
    si = m.group("si")
    nq = NQuad(subject=si if si is not None else m.group("sb"),
               predicate=m.group("pi") or m.group("pw"))
    lit = m.group("lit")
    if lit is not None:
        if "\\" in lit:
            lit = _unescape(lit)
        dtype = m.group("dt")
        if dtype:
            tid = _XS_TYPES.get(
                dtype.split("#")[-1] if "#" in dtype else dtype)
            nq.object_value = _coerce(
                lit, TypeID.STRING if tid is None else tid)
        else:
            nq.object_value = Val(TypeID.DEFAULT, lit)
        nq.lang = m.group("lang") or ""
    else:
        oi = m.group("oi")
        nq.object_id = oi if oi is not None else m.group("ob")
    return nq


def parse_rdf(text: str) -> list[NQuad]:
    """Parse N-Quad statements — '.'-terminated, possibly several per
    line (the grammar's terminator is the dot, not the newline).
    Ref: chunker.ParseRDFs / parseNQuad (chunker/rdf_parser.go:58).
    Trailing junk after a statement is an error, never silently
    dropped."""
    out: list[NQuad] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _FAST.match(line)
        if m is not None:
            out.append(_fast_nquad(m))
            continue
        while line and not line.startswith("#"):
            nq, rest = _parse_one(line, lineno)
            out.append(nq)
            line = rest.strip()
    return out


def _norm_func(raw: str, lineno: int, subject: bool) -> str:
    """Normalize `uid( v )`/`val( v )` upsert references to `uid(v)` form
    (ref chunker/rdf_parser.go uid/val function terms)."""
    kind = raw[:3]
    inner = raw[4:-1].strip()
    if subject and kind == "val":
        raise GQLError(f"rdf line {lineno}: val() not allowed as subject")
    return f"{kind}({inner})"


def _take(line: str, lineno: int):
    m = _TERM.match(line)
    if not m:
        raise GQLError(f"rdf line {lineno}: cannot parse at {line[:30]!r}")
    return m, line[m.end():]


def _parse_one(line: str, lineno: int) -> tuple[NQuad, str]:
    m, rest = _take(line, lineno)
    if m.group("iri"):
        subject = m.group("iri")[1:-1]
    elif m.group("blank"):
        subject = m.group("blank")
    elif m.group("func"):
        subject = _norm_func(m.group("func"), lineno, subject=True)
    elif m.group("word"):
        subject = m.group("word")
    else:
        raise GQLError(f"rdf line {lineno}: bad subject")

    m, rest = _take(rest, lineno)
    if m.group("star"):
        pred = "*"  # S * * — delete every predicate of S (expanded later)
    else:
        pred = (m.group("iri") or "")[1:-1] if m.group("iri") \
            else m.group("word")
    if not pred:
        raise GQLError(f"rdf line {lineno}: bad predicate")

    nq = NQuad(subject=subject, predicate=pred)
    m, rest = _take(rest, lineno)
    if m.group("literal") is not None:
        raw = _unescape(m.group("literal")[1:-1])
        dtype = m.group("dtype")
        if dtype:
            tid = _XS_TYPES.get(dtype.split("#")[-1] if "#" in dtype else dtype)
            if tid is None:
                tid = TypeID.STRING
            nq.object_value = _coerce(raw, tid)
        else:
            nq.object_value = Val(TypeID.DEFAULT, raw)
        nq.lang = m.group("lang") or ""
    elif m.group("star"):
        nq.star = True
    elif m.group("iri"):
        nq.object_id = m.group("iri")[1:-1]
    elif m.group("blank"):
        nq.object_id = m.group("blank")
    elif m.group("func"):
        f = _norm_func(m.group("func"), lineno, subject=False)
        if f.startswith("val("):
            nq.val_var = f[4:-1]
        else:
            nq.object_id = f
    elif m.group("word"):
        nq.object_id = m.group("word")

    # optional graph-label term (standard N-Quads 4th term; the
    # reference parses and discards it, chunker/rdf_parser.go label)
    rest = rest.strip()
    if rest.startswith("<"):
        m2 = _TERM.match(rest)
        if m2 and m2.group("iri"):
            rest = rest[m2.end():]

    # optional facets: ( key = value , ... )
    rest = rest.strip()
    if rest.startswith("("):
        end = rest.index(")")
        nq.facets.update(parse_facet_text(rest[1:end]))
        rest = rest[end + 1:]
    rest = rest.strip()
    if not rest.startswith("."):
        # '.' is the statement terminator — and with several statements
        # per line, the load-bearing separator; a missing dot must
        # error, not silently accept a truncated statement
        raise GQLError(
            f"rdf line {lineno}: statement not '.'-terminated at "
            f"{rest[:30]!r}")
    return nq, rest[1:]


def parse_facet_text(inner: str) -> dict[str, Val]:
    """`key = value, ...` between facet parens → typed facet dict.
    Shared by the python grammar and the native parser's facet spans
    (native.cc dgt_rdf_parse returns the span verbatim)."""
    out: dict[str, Val] = {}
    for part in inner.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = _facet_val(v.strip())
    return out


def _facet_val(raw: str) -> Val:
    """Facet values are type-inferred (ref chunker facets handling +
    types/facets/utils.go:129)."""
    if raw.startswith('"') and raw.endswith('"'):
        inner = _unescape(raw[1:-1])
        try:
            from dgraph_tpu.models.types import parse_datetime

            return Val(TypeID.DATETIME, parse_datetime(inner))
        except ValueError:
            return Val(TypeID.STRING, inner)
    if raw.lower() in ("true", "false"):
        return Val(TypeID.BOOL, raw.lower() == "true")
    try:
        return Val(TypeID.INT, int(raw))
    except ValueError:
        pass
    try:
        return Val(TypeID.FLOAT, float(raw))
    except ValueError:
        pass
    try:
        # unquoted RFC3339 tokens are datetime facets (ref
        # types/facets/utils.go:129 FacetFor's type sniffing; an
        # unparseable offset like +30:00 stays a string there too)
        from dgraph_tpu.models.types import parse_datetime

        return Val(TypeID.DATETIME, parse_datetime(raw))
    except ValueError:
        pass
    return Val(TypeID.STRING, raw)


# -- JSON mutations ----------------------------------------------------------


def parse_json_mutation(data: Any, *, delete: bool = False,
                        _counter: list | None = None) -> list[NQuad]:
    """JSON object(s) -> NQuads. Ref: chunker/json_parser.go mapToNquads.

    Maps use the "uid" key for node identity (auto blank node otherwise),
    nested objects become uid edges, lists fan out, `key|facet` keys attach
    facets, and `key@lang` sets the language tag.
    """
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    counter = _counter if _counter is not None else [0]
    out: list[NQuad] = []
    items = data if isinstance(data, list) else [data]
    for obj in items:
        _map_to_nquads(obj, out, counter, delete)
    return out


def _fresh_blank(counter: list) -> str:
    counter[0] += 1
    return f"_:dg.json.{counter[0]}"


def _json_val(v: Any) -> Val:
    if isinstance(v, bool):
        return Val(TypeID.BOOL, v)
    if isinstance(v, int):
        return Val(TypeID.INT, v)
    if isinstance(v, float):
        return Val(TypeID.FLOAT, v)
    if isinstance(v, dict):  # geojson value object
        return Val(TypeID.GEO, v)
    return Val(TypeID.DEFAULT, str(v))


def _map_to_nquads(obj: dict, out: list[NQuad], counter: list,
                   delete: bool) -> str:
    if not isinstance(obj, dict):
        raise GQLError(f"JSON mutation: expected object, got {obj!r}")
    uid = obj.get("uid") or _fresh_blank(counter)
    if isinstance(uid, int):
        uid = hex(uid)
    facets_by_pred: dict[str, dict[str, Val]] = {}
    plain: list[tuple[str, Any]] = []
    for key, v in obj.items():
        if key == "uid":
            continue
        if "|" in key:
            pred, _, fkey = key.partition("|")
            facets_by_pred.setdefault(pred, {})[fkey] = _json_val(v)
        else:
            plain.append((key, v))
    for key, v in plain:
        lang = ""
        pred = key
        if "@" in key:
            pred, _, lang = key.partition("@")
        facets = facets_by_pred.get(pred, {})
        if v is None:
            if delete:
                out.append(NQuad(subject=uid, predicate=pred, star=True))
            continue
        vals = v if isinstance(v, list) else [v]
        for item in vals:
            if isinstance(item, dict) and not _is_geojson(item):
                child = _map_to_nquads(item, out, counter, delete)
                out.append(NQuad(subject=uid, predicate=pred,
                                 object_id=child, facets=dict(facets)))
            elif isinstance(item, str) and item.startswith("val(") \
                    and item.endswith(")"):
                # upsert value substitution in JSON bodies —
                # {"bal": "val(n)"} behaves like `<s> <bal> val(n) .`
                # (ref edgraph/server.go:503 updateValInMutations works
                # on both body formats)
                out.append(NQuad(subject=uid, predicate=pred,
                                 val_var=item[4:-1], lang=lang,
                                 facets=dict(facets)))
            elif isinstance(item, str) and item.startswith("uid(") \
                    and item.endswith(")"):
                # {"friend": "uid(v)"} links to every uid in v
                out.append(NQuad(subject=uid, predicate=pred,
                                 object_id=item, facets=dict(facets)))
            else:
                out.append(NQuad(subject=uid, predicate=pred,
                                 object_value=_json_val(item), lang=lang,
                                 facets=dict(facets)))
    return uid


def _is_geojson(d: dict) -> bool:
    return "type" in d and "coordinates" in d


def nquad_to_wire(nq: NQuad) -> tuple:
    """NQuad -> wire-encodable tuple, for shipping parsed (already
    uid-resolved) triples between cluster processes — a text
    round-trip would re-risk escaping/precision; this keeps Vals
    typed (wire T_VAL). Inverse: nquad_from_wire."""
    return (nq.subject, nq.predicate, nq.object_id, nq.object_value,
            nq.lang, dict(nq.facets), nq.star, nq.val_var)


def nquad_from_wire(t) -> NQuad:
    s, p, oid, oval, lang, facets, star, val_var = t
    return NQuad(subject=s, predicate=p, object_id=oid,
                 object_value=oval, lang=lang, facets=dict(facets),
                 star=bool(star), val_var=val_var)
