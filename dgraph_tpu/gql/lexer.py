"""Query-language lexer.

The reference uses a Rob Pike-style state-function lexer (lex/lexer.go:42);
here a single master regex plus a token cursor gives the same token stream
with far less machinery — the parser is the interesting part.
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class GQLError(ValueError):
    pass


@dataclass(frozen=True)
class Token:
    kind: str
    val: str
    pos: int
    line: int


_MASTER = re.compile(
    r"""
      (?P<ws>\s+)
    | (?P<comment>\#[^\n]*)
    | (?P<string>"(?:\\.|[^"\\])*")
    | (?P<spread>\.\.\.)
    | (?P<iri><[^>\s]*>)
    | (?P<hex>0[xX][0-9a-fA-F]+)
    | (?P<number>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+[eE][+-]?\d+|-?\d+)
    | (?P<dollar>\$[A-Za-z_][\w]*)
    | (?P<name>[A-Za-z_~À-￿][\w.À-￿]*)
    | (?P<lbrace>\{) | (?P<rbrace>\})
    | (?P<lparen>\() | (?P<rparen>\))
    | (?P<lbracket>\[) | (?P<rbracket>\])
    | (?P<colon>:) | (?P<comma>,) | (?P<at>@) | (?P<pipe>\|)
    | (?P<op><=|>=|==|!=|[+\-*/%<>=!])
    | (?P<star>\*)
    | (?P<dot>\.)
    """,
    re.VERBOSE,
)


def tokenize(text: str) -> list[Token]:
    toks: list[Token] = []
    pos = 0
    line = 1
    n = len(text)
    while pos < n:
        # contextual regex literal, the reference's lexer-state trick
        # (lex/lexer.go regexp state): a '/' opening a function
        # argument (right after '(' or ',') starts /pattern/flags —
        # scanned manually so ^ $ \d \/ # and friends all pass
        # through; '/' anywhere else stays the division operator
        if text[pos] == "/" and toks and \
                toks[-1].kind in ("lparen", "comma"):
            tok, pos = _scan_regex(text, pos, line)
            toks.append(tok)
            continue
        m = _MASTER.match(text, pos)
        if m is None:
            raise GQLError(
                f"line {line}: unexpected character {text[pos]!r}")
        kind = m.lastgroup
        val = m.group()
        line += val.count("\n")
        pos = m.end()
        if kind in ("ws", "comment"):
            continue
        if kind == "string":
            val = _unquote(val, line)
        elif kind == "iri":
            val = val[1:-1]
            kind = "name"
        toks.append(Token(kind, val, m.start(), line))
    toks.append(Token("eof", "", n, line))
    return toks


def _scan_regex(text: str, pos: int, line: int) -> tuple[Token, int]:
    """Scan /pattern/flags starting at the opening slash. The pattern
    body keeps its backslashes verbatim (the regex engine interprets
    them; \\/ escapes the delimiter, like the reference)."""
    i = pos + 1
    n = len(text)
    while i < n:
        c = text[i]
        if c == "\\" and i + 1 < n:
            i += 2
            continue
        if c == "/":
            break
        if c == "\n":
            raise GQLError(
                f"line {line}: newline inside regex literal")
        i += 1
    else:
        raise GQLError(f"line {line}: unterminated regex literal")
    body = text[pos + 1 : i]
    i += 1
    flags = ""
    while i < n and text[i].isalpha():
        flags += text[i]
        i += 1
    return Token("regex", body + "\x00" + flags, pos, line), i


_ESCAPES = {
    '"': '"', "\\": "\\", "/": "/", "n": "\n", "t": "\t", "r": "\r",
    "b": "\b", "f": "\f", "'": "'",
}


def _unquote(raw: str, line: int) -> str:
    body = raw[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\":
            i += 1
            if i >= len(body):
                raise GQLError(f"line {line}: dangling escape in string")
            e = body[i]
            if e == "u":
                out.append(chr(int(body[i + 1 : i + 5], 16)))
                i += 4
            else:
                out.append(_ESCAPES.get(e, e))
        else:
            out.append(c)
        i += 1
    return "".join(out)


class Cursor:
    def __init__(self, toks: list[Token], src: str = ""):
        self.toks = toks
        self.src = src
        self.i = 0

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.peek()
        if t.kind == "eof":
            # consuming past the end must error, not return eof forever:
            # `while not accept(...)` loops would otherwise spin on
            # truncated input (found by the fuzz suite)
            raise GQLError(f"line {t.line}: unexpected end of input")
        self.i += 1
        return t

    def accept(self, kind: str, val: str | None = None) -> Token | None:
        t = self.peek()
        if t.kind == kind and (val is None or t.val == val):
            return self.next()
        return None

    def expect(self, kind: str, what: str = "") -> Token:
        t = self.next()
        if t.kind != kind:
            raise GQLError(
                f"line {t.line}: expected {what or kind}, got "
                f"{t.kind} {t.val!r}")
        return t
