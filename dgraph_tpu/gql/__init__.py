"""GraphQL± front-end: lexer, AST, parser, mutation (RDF/JSON) parsing.

Re-provides the reference's `gql/` + `lex/` packages (gql/parser.go:524
Parse, gql/parser_mutation.go:26 ParseMutation) as a Python recursive-
descent parser. Pure library: no dependencies on the engine below it.
"""

from dgraph_tpu.gql.ast import (
    Arg,
    FilterTree,
    Function,
    GraphQuery,
    Order,
    ParsedResult,
    RecurseArgs,
    ShortestArgs,
    VarContext,
)
from dgraph_tpu.gql.parser import GQLError, parse
from dgraph_tpu.gql.nquad import NQuad, parse_rdf, parse_json_mutation
