"""GraphQL± recursive-descent parser.

Covers the reference's query surface (gql.Parse, gql/parser.go:524):
query blocks with root functions, GraphQL variables, fragments, filters
with and/or/not, pagination (first/offset/after), multi-key ordering,
aliases, language tags, count blocks, value/uid variables (`x as ...`),
aggregations (min/max/sum/avg), math blocks, groupby, facets, expand(),
@recurse, @cascade, @normalize, @ignorereflex, and shortest-path blocks.

Built as a fresh recursive-descent over a regex token stream — not a
translation of the reference's lexer-state machinery.
"""

from __future__ import annotations

from dgraph_tpu.gql.ast import (
    ANY_VAR, UID_VAR, VALUE_VAR,
    Arg, FacetParams, FilterTree, Function, GraphQuery, GroupByAttr,
    MathTree, Order, ParsedResult, RecurseArgs, ShortestArgs, VarContext,
)
from dgraph_tpu.gql.lexer import Cursor, GQLError, Token, tokenize

_ROOT_FUNCS = {
    "eq", "le", "lt", "ge", "gt", "between", "has", "uid", "uid_in",
    "anyofterms", "allofterms", "anyoftext", "alloftext", "regexp",
    "match", "near", "within", "contains", "intersects", "type",
    "anyof", "allof", "similar_to",
}
_AGG_FUNCS = {"min", "max", "sum", "avg"}
# every name _parse_function accepts (root funcs + the filter-capable
# extras; the executor rejects len() outside @filter)
_QUERY_FUNCS = _ROOT_FUNCS | {"checkpwd", "len"}
_DIRECTIVES = {"filter", "facets", "cascade", "normalize", "ignorereflex",
               "recurse", "groupby"}
_BOOL_OPS = {"and", "or", "not"}

def _to_int(raw: str, line: int = 0) -> int:
    """Numeric literal -> int with a clean GQLError on junk the lexer
    let through (e.g. '020000': base-0 rejects leading zeros — found by
    the fuzz suite, ref gql/parser_fuzz.go contract)."""
    try:
        return int(raw, 0)
    except ValueError as e:
        raise GQLError(f"line {line}: bad integer literal {raw!r}") from e



def parse(text: str, variables: dict | None = None) -> ParsedResult:
    """Parse a full query document.  `variables` supplies values for
    GraphQL `$vars` (ref gql.Request.Variables)."""
    cur = Cursor(tokenize(text), src=text)
    vars_decl: dict[str, str | None] = {}
    res = ParsedResult()
    fragments: dict[str, GraphQuery] = {}

    while cur.peek().kind != "eof":
        t = cur.peek()
        if t.kind == "at":
            # document-level `@explain` / `@explain(analyze: true)`:
            # the request asks for its compiled plan tree (EXPLAIN) or
            # the executed-and-measured version (EXPLAIN ANALYZE) in
            # extensions.explain. A flag on the request, not a query
            # block — execution itself is unchanged.
            cur.next()
            d = cur.expect("name", "directive").val.lower()
            if d != "explain":
                raise GQLError(
                    f"line {t.line}: unknown document directive @{d}")
            mode = "plan"
            if cur.accept("lparen"):
                key = cur.expect("name", "explain option").val.lower()
                cur.expect("colon")
                val = cur.next().val.lower()
                cur.expect("rparen")
                if key != "analyze":
                    raise GQLError(
                        f"line {t.line}: unknown @explain option "
                        f"{key!r} (only 'analyze')")
                if val == "true":
                    mode = "analyze"
                elif val != "false":
                    raise GQLError(
                        f"line {t.line}: @explain(analyze:) must be "
                        f"true or false, got {val!r}")
            # repeated directives keep the STRONGER mode — same rule
            # the transport-flag/document-directive combiner applies
            if res.explain != "analyze":
                res.explain = mode
        elif t.kind == "name" and t.val == "query":
            cur.next()
            if cur.peek().kind == "name":  # optional op name
                cur.next()
            if cur.peek().kind == "lparen":
                vars_decl = _parse_var_decls(cur)
            _parse_block_set(cur, res, _resolve_vars(vars_decl, variables))
        elif t.kind == "name" and t.val == "fragment":
            cur.next()
            name = cur.expect("name", "fragment name").val
            frag = GraphQuery(attr=f"fragment/{name}")
            cur.expect("lbrace")
            _parse_selection_set(cur, frag, {})
            fragments[name] = frag
        elif t.kind == "lbrace":
            _parse_block_set(cur, res, _resolve_vars(vars_decl, variables))
        elif t.kind == "name" and t.val == "schema":
            # bare `schema {}` / `schema(pred: [..]) { fields }` at the
            # document top level (ref gql parser's schema handling)
            cur.next()
            _parse_schema_block(cur, res)
        else:
            raise GQLError(
                f"line {t.line}: unexpected {t.val!r} at document top level")

    for q in res.queries:
        _expand_fragments(q, fragments, set())
        _collect_needs(q, res)
    _check_duplicates(res)
    return res


def _check_duplicates(res: ParsedResult):
    """Reject duplicate emitting-block aliases and vars defined more
    than once (ref gql/parser.go validate: 'Duplicate aliases not
    allowed' + 'Variable ... defined multiple times') — accepting them
    silently drops or shadows one block's results."""
    names: set[str] = set()
    seen_vars: set[str] = set()

    def walk(gq):
        if gq.var:
            if gq.var in seen_vars:
                raise GQLError(
                    f"variable {gq.var!r} is defined multiple times")
            seen_vars.add(gq.var)
        for v in (gq.facet_var or {}).values():
            if v in seen_vars:
                raise GQLError(
                    f"variable {v!r} is defined multiple times")
            seen_vars.add(v)
        for c in gq.children:
            walk(c)

    for q in res.queries:
        nm = q.alias or q.attr
        if nm and nm not in ("var", "shortest"):
            if nm in names:
                raise GQLError(f"duplicate query alias {nm!r}")
            names.add(nm)
        walk(q)


def _resolve_vars(decl: dict, provided: dict | None) -> dict[str, str]:
    out = {}
    # clients pass keys with the dollar sign ("$a": "2" — the
    # reference's api.Request.Vars convention); decls store bare
    # names. Strip ONE leading "$" ("$$a" must stay "$a", not collapse
    # to "a"), and reject a bare/"$"-prefixed duplicate pair — which
    # key wins would otherwise be dict-order roulette (ADVICE round 5)
    norm: dict[str, str] = {}
    for k, v in (provided or {}).items():
        key = k[1:] if k.startswith("$") else k
        if key in norm:
            raise GQLError(
                f"duplicate GraphQL variable {key!r} "
                "(supplied both bare and $-prefixed)")
        norm[key] = v
    provided = norm
    for name, default in decl.items():
        if name in provided:
            out[name] = str(provided[name])
        elif default is not None:
            out[name] = default
        else:
            raise GQLError(f"variable {name} not supplied and has no default")
    # allow extra provided vars even without declaration (reference is
    # stricter; being lenient here only widens accepted inputs)
    for k, v in provided.items():
        out.setdefault(k, str(v))
    return out


def _parse_var_decls(cur: Cursor) -> dict[str, str | None]:
    cur.expect("lparen")
    out: dict[str, str | None] = {}
    while not cur.accept("rparen"):
        tok = cur.expect("dollar", "$variable")
        cur.expect("colon")
        cur.expect("name", "variable type")  # int/float/bool/string — unused
        if cur.accept("op", "="):
            d = cur.next()
            out[tok.val[1:]] = d.val
        else:
            out[tok.val[1:]] = None
        cur.accept("comma")
    return out


def _parse_block_set(cur: Cursor, res: ParsedResult, gvars: dict):
    cur.expect("lbrace")
    while not cur.accept("rbrace"):
        t = cur.peek()
        if t.kind == "name" and t.val == "schema":
            cur.next()
            _parse_schema_block(cur, res)
            continue
        res.queries.append(_parse_block(cur, gvars))


def _parse_schema_block(cur: Cursor, res: ParsedResult):
    """`schema {}` / `schema(pred: [name, age]) { type index tokenizer }`
    — schema introspection through the query language (ref gql
    schema-block parsing; query response carries a "schema" array)."""
    preds: list[str] = []
    fields: list[str] = []
    if cur.accept("lparen"):
        key = cur.expect("name", "schema arg").val
        if key != "pred":
            raise GQLError(f"schema block: unknown argument {key!r}")
        cur.expect("colon")
        if cur.accept("lbracket"):
            while not cur.accept("rbracket"):
                tok = cur.next()
                if tok.kind not in ("name", "string"):
                    raise GQLError(
                        f"line {tok.line}: schema pred list expects "
                        f"predicate names, got {tok.val!r}")
                preds.append(tok.val.strip('"'))
                cur.accept("comma")
        else:
            tok = cur.next()
            if tok.kind not in ("name", "string"):
                raise GQLError(
                    f"line {tok.line}: schema pred expects a "
                    f"predicate name, got {tok.val!r}")
            preds.append(tok.val.strip('"'))
        cur.expect("rparen")
    cur.expect("lbrace")
    while not cur.accept("rbrace"):
        fields.append(cur.expect("name", "schema field").val)
    if res.schema_request is not None:
        raise GQLError("only one schema block per query")
    res.schema_request = {"preds": preds, "fields": fields}


def _parse_block(cur: Cursor, gvars: dict) -> GraphQuery:
    gq = GraphQuery()
    name_tok = cur.expect("name", "query block name")
    # `x as blockname(...)` defines a block-level uid var
    if cur.peek().kind == "name" and cur.peek().val == "as":
        cur.next()
        gq.var = name_tok.val
        name_tok = cur.expect("name", "query block name")
    gq.alias = name_tok.val

    if name_tok.val == "shortest":
        gq.attr = "shortest"
        gq.shortest = _parse_shortest_args(cur, gvars)
    else:
        if cur.peek().kind == "lparen":
            _parse_root_args(cur, gq, gvars)
        else:
            gq.is_empty = True
    while cur.peek().kind == "at":
        _parse_directive(cur, gq, gvars)
    if cur.peek().kind == "lbrace":
        cur.next()
        _parse_selection_set(cur, gq, gvars)
    return gq


def _parse_root_args(cur: Cursor, gq: GraphQuery, gvars: dict):
    cur.expect("lparen")
    while not cur.accept("rparen"):
        key = cur.expect("name", "root argument").val
        cur.expect("colon")
        if key == "func":
            gq.func = _parse_function(cur, gvars)
            if gq.func.name == "uid":
                gq.uids = list(gq.func.uids)
                for v in gq.func.needs_var:
                    gq.needs_var.append(v)
        elif key in ("first", "offset", "after"):
            _set_pagination(gq, key, _scalar_str(cur, gvars))
        elif key in ("orderasc", "orderdesc"):
            attr, lang = _pred_with_lang_str(cur)
            gq.order.append(Order(attr, desc=(key == "orderdesc"), lang=lang))
        elif key == "id":
            raise GQLError("id argument was removed; use func: uid(...)")
        else:
            raise GQLError(f"unknown root argument {key!r}")
        cur.accept("comma")
    if gq.func is None and not gq.uids and not gq.needs_var:
        gq.is_empty = True


def _set_pagination(gq: GraphQuery, key: str, raw: str):
    try:
        v = _to_int(raw)
    except ValueError as e:
        raise GQLError(f"{key} must be an integer, got {raw!r}") from e
    if key == "first":
        gq.first = v
    elif key == "offset":
        gq.offset = v
    else:
        gq.after = v


def _scalar_str(cur: Cursor, gvars: dict) -> str:
    t = cur.next()
    if t.kind == "dollar":
        name = t.val[1:]
        if name not in gvars:
            raise GQLError(f"undefined GraphQL variable ${name}")
        return gvars[name]
    if t.kind in ("number", "string", "name", "hex"):
        return t.val
    raise GQLError(f"line {t.line}: expected scalar, got {t.val!r}")


def _pred_with_lang_str(cur: Cursor) -> tuple[str, str]:
    """`pred` or `pred@lang` or val(x) for order args."""
    t = cur.expect("name", "predicate")
    if t.val == "val" and cur.peek().kind == "lparen":
        cur.next()
        v = cur.expect("name", "variable").val
        cur.expect("rparen")
        return f"val({v})", ""
    lang = ""
    if cur.accept("at"):
        lang = "." if cur.accept("dot") \
            else cur.expect("name", "language").val
    return t.val, lang


# -- functions ---------------------------------------------------------------


def _parse_function(cur: Cursor, gvars: dict) -> Function:
    name_tok = cur.expect("name", "function name")
    fname = name_tok.val.lower()
    if fname not in _QUERY_FUNCS:
        # min/max etc. are not query functions (ref gql
        # validateFunction: "Function name: min is not valid" —
        # query0:TestVarInAggError). len() is only legal inside
        # @filter, which the executor enforces.
        raise GQLError(
            f"line {name_tok.line}: function name {fname!r} "
            "is not valid")
    fn = Function(name=fname)
    cur.expect("lparen")

    if fname == "uid":
        while not cur.accept("rparen"):
            t = cur.next()
            if t.kind in ("hex", "number"):
                fn.uids.append(_to_int(t.val, t.line))
            elif t.kind == "name":
                fn.needs_var.append(VarContext(t.val, UID_VAR))
            else:
                raise GQLError(f"line {t.line}: bad uid() argument {t.val!r}")
            cur.accept("comma")
        return fn
    if fname == "type":
        fn.args.append(Arg(cur.expect("name", "type name").val))
        cur.expect("rparen")
        return fn

    # first argument: attribute | count(attr) | val(var) | len(var) | uid
    t = cur.peek()
    if t.kind == "name" and t.val == "count":
        cur.next()
        cur.expect("lparen")
        fn.attr = cur.expect("name", "attribute").val
        cur.expect("rparen")
        fn.is_count = True
    elif t.kind == "name" and t.val == "val":
        cur.next()
        cur.expect("lparen")
        v = cur.expect("name", "variable").val
        fn.needs_var.append(VarContext(v, VALUE_VAR))
        fn.is_value_var = True
        cur.expect("rparen")
    elif t.kind == "name" and t.val == "len":
        cur.next()
        cur.expect("lparen")
        v = cur.expect("name", "variable").val
        fn.needs_var.append(VarContext(v, ANY_VAR))
        fn.is_len_var = True
        cur.expect("rparen")
    else:
        fn.attr = cur.expect("name", "attribute").val
        if cur.accept("at"):
            # `pred@en` or `pred@.` (any language)
            fn.lang = "." if cur.accept("dot") \
                else cur.expect("name", "language").val

    cur.accept("comma")
    while not cur.accept("rparen"):
        t = cur.next()
        if t.kind == "lbracket" and fname in (
                "near", "within", "contains", "intersects",
                "similar_to"):
            # geo coordinate / vector literal: keep the (possibly
            # nested) list structure as one argument (ref
            # gql/parser.go parseGeoArgs; similar_to's query vector
            # may be a bare [0.1, 0.2, ...] literal like Dgraph's)
            fn.args.append(Arg(_parse_coord_list(cur)))
        elif t.kind == "lbracket":
            while not cur.accept("rbracket"):
                inner = cur.next()
                if inner.kind == "dollar":
                    fn.args.append(Arg(gvars[inner.val[1:]], is_graphql_var=True))
                elif inner.kind == "name" and inner.val == "val":
                    cur.expect("lparen")
                    v = cur.expect("name").val
                    cur.expect("rparen")
                    fn.needs_var.append(VarContext(v, VALUE_VAR))
                    fn.args.append(Arg(v, is_value_var=True))
                else:
                    fn.args.append(Arg(inner.val))
                cur.accept("comma")
        elif t.kind == "dollar":
            name = t.val[1:]
            if name not in gvars:
                raise GQLError(f"undefined GraphQL variable ${name}")
            val = gvars[name]
            if fname == "regexp":
                # a regexp argument supplied via GraphQL variable
                # carries the /pattern/flags form (ref query4:
                # TestRegExpVariableReplacement); require BOTH
                # slashes like the literal lexer does — "/i" must not
                # silently become an empty match-everything pattern
                if len(val) < 2 or not val.startswith("/") \
                        or "/" not in val[1:]:
                    raise GQLError(
                        f"regexp variable ${name} must carry "
                        f"/pattern/flags, got {val!r}")
                body, _, flags = val[1:].rpartition("/")
                if not body:
                    # "//i" would otherwise compile to an empty
                    # match-everything pattern (ADVICE round 5)
                    raise GQLError(
                        f"regexp variable ${name} has an empty "
                        f"pattern body, got {val!r}")
                fn.args.append(Arg(body))
                if flags:
                    fn.args.append(Arg(flags))
            else:
                fn.args.append(Arg(val, is_graphql_var=True))
        elif t.kind == "name" and t.val == "val" and cur.peek().kind == "lparen":
            cur.next()
            v = cur.expect("name", "variable").val
            cur.expect("rparen")
            fn.needs_var.append(VarContext(v, VALUE_VAR))
            fn.args.append(Arg(v, is_value_var=True))
        elif t.kind == "name" and t.val == "uid" and cur.peek().kind == "lparen":
            # uid_in(pred, uid(v)) form
            cur.next()
            while not cur.accept("rparen"):
                u = cur.next()
                if u.kind in ("hex", "number"):
                    fn.uids.append(_to_int(u.val, u.line))
                else:
                    fn.needs_var.append(VarContext(u.val, UID_VAR))
                cur.accept("comma")
        elif t.kind in ("string", "number", "hex", "name"):
            if fname in ("uid_in",) and t.kind in ("hex", "number"):
                fn.uids.append(_to_int(t.val, t.line))
            else:
                fn.args.append(Arg(t.val))
        elif t.kind == "regex":
            # /pattern/flags scanned contextually by the lexer
            pat, _, flags = t.val.partition("\x00")
            fn.args.append(Arg(pat))
            if flags:
                fn.args.append(Arg(flags))
        else:
            raise GQLError(f"line {t.line}: bad function argument {t.val!r}")
        cur.accept("comma")
    return fn


def _parse_coord_list(cur: Cursor) -> list:
    """After an opening '[': numbers / nested lists until ']'."""
    out: list = []
    while not cur.accept("rbracket"):
        t = cur.next()
        if t.kind == "lbracket":
            out.append(_parse_coord_list(cur))
        elif t.kind == "number":
            out.append(float(t.val))
        else:
            raise GQLError(
                f"line {t.line}: bad coordinate literal {t.val!r}")
        cur.accept("comma")
    return out


# -- filters -----------------------------------------------------------------


def _parse_filter(cur: Cursor, gvars: dict) -> FilterTree:
    cur.expect("lparen")
    tree = _parse_filter_or(cur, gvars)
    cur.expect("rparen")
    return tree


def parse_cond(text: str) -> FilterTree | None:
    """Parse an upsert conditional mutation's `@if(...)` expression
    (ref gql.ParseMutation conditional handling, gql/parser_mutation.go:26
    + edgraph/server.go:220 doMutate cond evaluation)."""
    text = (text or "").strip()
    if not text:
        return None
    if text.startswith("@if"):
        text = text[3:].lstrip()
    cur = Cursor(tokenize(text), src=text)
    tree = _parse_filter(cur, {})
    t = cur.peek()
    if t.kind != "eof":
        raise GQLError(f"line {t.line}: trailing input in @if condition")
    return tree


def _parse_filter_or(cur: Cursor, gvars: dict) -> FilterTree:
    left = _parse_filter_and(cur, gvars)
    children = [left]
    while _peek_bool_op(cur) == "or":
        cur.next()
        children.append(_parse_filter_and(cur, gvars))
    if len(children) == 1:
        return left
    return FilterTree(op="or", children=children)


def _parse_filter_and(cur: Cursor, gvars: dict) -> FilterTree:
    left = _parse_filter_unary(cur, gvars)
    children = [left]
    while _peek_bool_op(cur) == "and":
        cur.next()
        children.append(_parse_filter_unary(cur, gvars))
    if len(children) == 1:
        return left
    return FilterTree(op="and", children=children)


def _parse_filter_unary(cur: Cursor, gvars: dict) -> FilterTree:
    if _peek_bool_op(cur) == "not":
        cur.next()
        return FilterTree(op="not", children=[_parse_filter_unary(cur, gvars)])
    if cur.peek().kind == "lparen":
        cur.next()
        t = _parse_filter_or(cur, gvars)
        cur.expect("rparen")
        return t
    fn = _parse_function(cur, gvars)
    return FilterTree(func=fn)


def _peek_bool_op(cur: Cursor) -> str | None:
    t = cur.peek()
    if t.kind == "name" and t.val.lower() in _BOOL_OPS:
        # 'not' must be followed by a function or '(' to count as an op
        return t.val.lower()
    return None


# -- directives --------------------------------------------------------------


def _parse_directive(cur: Cursor, gq: GraphQuery, gvars: dict):
    cur.expect("at")
    name = cur.expect("name", "directive").val.lower()
    if name == "filter":
        gq.filter = _parse_filter(cur, gvars)
    elif name == "cascade":
        gq.cascade = True
    elif name == "normalize":
        gq.normalize = True
    elif name == "ignorereflex":
        gq.ignore_reflex = True
    elif name == "recurse":
        ra = RecurseArgs()
        if cur.peek().kind == "lparen":
            cur.next()
            while not cur.accept("rparen"):
                key = cur.expect("name", "recurse arg").val
                cur.expect("colon")
                val = _scalar_str(cur, gvars)
                if key == "depth":
                    ra.depth = _to_int(val)
                elif key == "loop":
                    ra.allow_loop = val.lower() == "true"
                else:
                    raise GQLError(f"unknown recurse arg {key!r}")
                cur.accept("comma")
        gq.recurse = ra
    elif name == "groupby":
        gq.is_groupby = True
        cur.expect("lparen")
        while not cur.accept("rparen"):
            attr_tok = cur.expect("name", "groupby attr")
            alias = ""
            attr = attr_tok.val
            if cur.accept("colon"):
                alias = attr
                attr = cur.expect("name").val
            lang = ""
            if cur.accept("at"):
                lang = cur.expect("name").val
            gq.groupby.append(GroupByAttr(attr, alias, lang))
            cur.accept("comma")
    elif name == "facets":
        _parse_facets(cur, gq, gvars)
    else:
        raise GQLError(f"unknown directive @{name}")


def _parse_facets(cur: Cursor, gq: GraphQuery, gvars: dict):
    fp = gq.facets or FacetParams()
    if cur.peek().kind != "lparen":
        fp.all_keys = True
        gq.facets = fp
        return
    # Could be @facets(key1, alias: key2), @facets(eq(key, v)) filter,
    # @facets(v as key) var, or @facets(orderasc: key)
    save = cur.i
    cur.next()
    first = cur.peek()
    if first.kind == "name" and first.val.lower() in (
            "eq", "le", "lt", "ge", "gt", "allofterms", "anyofterms",
            "not", "and", "or"):
        cur.i = save
        gq.facets_filter = _parse_filter(cur, gvars)
        return
    while not cur.accept("rparen"):
        t = cur.expect("name", "facet key")
        if cur.peek().kind == "name" and cur.peek().val == "as":
            cur.next()
            key = cur.expect("name").val
            gq.facet_var[key] = t.val
        elif t.val in ("orderasc", "orderdesc") and cur.peek().kind == "colon":
            cur.next()
            key = cur.expect("name").val
            if any(not o.attr.startswith("facet:") for o in gq.order):
                # ordering by a predicate AND a facet together is
                # ambiguous (ref query0:TestDoubleOrder rejects it)
                raise GQLError(
                    "cannot order by both a predicate and a facet")
            # bare selection: alias None (an explicit alias — even one
            # spelled like its key — emits under the BARE alias; ref
            # facets:TestFacetsAlias)
            fp.keys.append((key, None))
            gq.order.append(Order(f"facet:{key}", desc=(t.val == "orderdesc")))
        elif cur.accept("colon"):
            key = cur.expect("name").val
            fp.keys.append((key, t.val))
        else:
            fp.keys.append((t.val, None))
        cur.accept("comma")
    gq.facets = fp


# -- shortest ----------------------------------------------------------------


def _parse_shortest_args(cur: Cursor, gvars: dict) -> ShortestArgs:
    sa = ShortestArgs()
    cur.expect("lparen")
    while not cur.accept("rparen"):
        key = cur.expect("name", "shortest arg").val
        cur.expect("colon")
        if key in ("from", "to"):
            t = cur.peek()
            fn = Function(name="uid")
            if t.kind in ("hex", "number"):
                cur.next()
                fn.uids.append(_to_int(t.val, t.line))
            elif t.kind == "name" and t.val == "uid":
                fn = _parse_function(cur, gvars)
            else:
                raise GQLError(f"bad shortest {key}: {t.val!r}")
            if key == "from":
                sa.from_ = fn
            else:
                sa.to = fn
        elif key == "numpaths":
            sa.numpaths = _to_int(_scalar_str(cur, gvars))
        elif key == "depth":
            sa.depth = _to_int(_scalar_str(cur, gvars))
        elif key == "minweight":
            sa.minweight = float(_scalar_str(cur, gvars))
        elif key == "maxweight":
            sa.maxweight = float(_scalar_str(cur, gvars))
        else:
            raise GQLError(f"unknown shortest arg {key!r}")
        cur.accept("comma")
    return sa


# -- selection sets ----------------------------------------------------------


def _parse_selection_set(cur: Cursor, parent: GraphQuery, gvars: dict):
    while not cur.accept("rbrace"):
        t = cur.peek()
        if t.kind == "spread":
            cur.next()
            frag = cur.expect("name", "fragment name").val
            parent.children.append(GraphQuery(attr=f"fragment/{frag}"))
            continue
        if t.kind != "name":
            raise GQLError(
                f"line {t.line}: expected predicate, got {t.val!r}")
        parent.children.append(_parse_selection(cur, gvars))


def _parse_selection(cur: Cursor, gvars: dict) -> GraphQuery:
    gq = GraphQuery()
    first = cur.expect("name")

    # `v as pred` variable binding
    if cur.peek().kind == "name" and cur.peek().val == "as":
        cur.next()
        gq.var = first.val
        first = cur.expect("name", "predicate after 'as'")

    # alias `alias : pred` (not `pred: lang` — langs use @)
    if cur.peek().kind == "colon":
        cur.next()
        gq.alias = first.val
        first = cur.expect("name", "predicate after alias")

    name = first.val

    if name == "count" and cur.peek().kind == "lparen":
        cur.next()
        inner = cur.expect("name", "count target")
        if inner.val == "val":
            raise GQLError("count(val(...)) is not supported; "
                           "aggregate through a var block")
        if inner.val == "uid":
            gq.attr = "uid"
            gq.is_count = True
            gq.is_internal = True
        else:
            gq.attr = inner.val
            gq.is_count = True
            for _ in range(2):  # @lang, then optionally @filter
                if not cur.accept("at"):
                    break
                if cur.peek().kind == "name" \
                        and cur.peek().val.lower() == "filter":
                    # count(pred @filter(...)) counts only the edges
                    # the filter keeps (ref query0_test.go
                    # TestQueryEmptyRoomsWithTermIndex)
                    cur.next()
                    gq.filter = _parse_filter(cur, gvars)
                    break
                gq.langs = _parse_langs(cur)
            if cur.peek().kind == "lparen":
                # count(pred ... (orderasc: dob)): ordering never
                # changes a count — parse and discard (ref
                # query2_test.go TestToFastJSONOrderDescCount)
                depth = 0
                while True:
                    t = cur.next()
                    if t.kind == "lparen":
                        depth += 1
                    elif t.kind == "rparen":
                        depth -= 1
                        if depth == 0:
                            break
                    elif t.kind == "eof":
                        raise GQLError("unbalanced count() arguments")
        cur.expect("rparen")
    elif name == "checkpwd" and cur.peek().kind == "lparen":
        # checkpwd(pred, "plain") as a result field emits
        # `checkpwd(pred): bool` per row (ref query3:TestCheckPassword)
        cur.next()
        pred = cur.expect("name", "password predicate").val
        cur.expect("comma")
        pwd = cur.expect("string", "password string")
        cur.expect("rparen")
        gq.attr = pred
        gq.checkpwd_pwd = pwd.val
        gq.is_internal = True
    elif name in _AGG_FUNCS and cur.peek().kind == "lparen":
        cur.next()
        gq.agg_func = name
        inner = cur.expect("name", "val")
        if inner.val == "val":
            cur.expect("lparen")
            v = cur.expect("name").val
            cur.expect("rparen")
            cur.expect("rparen")
            gq.attr = f"{name}(val({v}))"
            gq.needs_var.append(VarContext(v, VALUE_VAR))
            gq.is_internal = True
        else:
            # max(name) etc: aggregate a PREDICATE's values — only
            # meaningful inside @groupby (ref query0_test.go
            # TestGroupByAgg); the executor rejects it elsewhere
            gq.attr = inner.val
            gq.agg_pred = inner.val
            cur.expect("rparen")
            gq.is_internal = True
    elif name == "val" and cur.peek().kind == "lparen":
        cur.next()
        v = cur.expect("name").val
        cur.expect("rparen")
        gq.attr = f"val({v})"
        gq.needs_var.append(VarContext(v, VALUE_VAR))
        gq.is_internal = True
    elif name == "uid" and cur.peek().kind == "lparen":
        cur.next()
        while not cur.accept("rparen"):
            u = cur.next()
            if u.kind in ("hex", "number"):
                gq.uids.append(_to_int(u.val, u.line))
            else:
                gq.needs_var.append(VarContext(u.val, UID_VAR))
            cur.accept("comma")
        gq.attr = "uid"
        gq.is_internal = True
    elif name == "math" and cur.peek().kind == "lparen":
        gq.attr = "math"
        gq.is_internal = True
        gq.math = _parse_math(cur)
    elif name == "expand" and cur.peek().kind == "lparen":
        cur.next()
        t = cur.next()
        gq.attr = "expand"
        gq.expand = t.val  # _all_ | type name(s) | var
        if t.kind == "name" and t.val == "val":
            cur.expect("lparen")
            gq.expand = cur.expect("name").val
            cur.expect("rparen")
        else:
            # expand(CarModel, Object): union of several types'
            # fields (ref query4_test.go
            # TestTypeExpandMultipleExplicitTypes)
            while cur.accept("comma"):
                gq.expand += "," + cur.expect("name").val
        cur.expect("rparen")
    else:
        gq.attr = name
        if cur.peek().kind == "at" and (
                cur.peek(1).kind == "dot"
                or cur.peek(1).val == "*"
                or (cur.peek(1).kind == "name"
                    and cur.peek(1).val.lower() not in _DIRECTIVES)):
            cur.next()
            gq.langs = _parse_langs(cur)

    # argument list (first/offset/after/orderasc/orderdesc)
    if cur.peek().kind == "lparen":
        cur.next()
        while not cur.accept("rparen"):
            key = cur.expect("name", "argument").val
            cur.expect("colon")
            if key in ("first", "offset", "after"):
                _set_pagination(gq, key, _scalar_str(cur, gvars))
            elif key in ("orderasc", "orderdesc"):
                attr, lang = _pred_with_lang_str(cur)
                gq.order.append(
                    Order(attr, desc=(key == "orderdesc"), lang=lang))
            else:
                raise GQLError(f"unknown argument {key!r}")
            cur.accept("comma")

    while cur.peek().kind == "at":
        _parse_directive(cur, gq, gvars)

    if cur.peek().kind == "lbrace":
        cur.next()
        _parse_selection_set(cur, gq, gvars)
    return gq


def _parse_langs(cur: Cursor) -> list[str]:
    # `name@en:fr`, `name@.` (any-language fallback), `name@en:.`,
    # `name@*` (every language as its own output key)
    langs = []
    if cur.accept("dot"):
        langs.append(".")
    elif cur.peek().val == "*":
        cur.next()
        return ["*"]
    else:
        langs.append(cur.expect("name", "language").val)
    while cur.accept("colon"):
        if cur.accept("dot"):
            langs.append(".")
        else:
            langs.append(cur.expect("name", "language").val)
    return langs


# -- math --------------------------------------------------------------------

_MATH_PREC = {
    "+": 1, "-": 1, "*": 2, "/": 2, "%": 2,
    "<": 0, ">": 0, "<=": 0, ">=": 0, "==": 0, "!=": 0,
}
_MATH_FUNCS = {"exp", "ln", "sqrt", "floor", "ceil", "cond", "pow",
               "logbase", "max", "min", "since", "sigmoid"}


def _parse_math(cur: Cursor) -> MathTree:
    cur.expect("lparen")
    tree = _parse_math_expr(cur, 0)
    cur.expect("rparen")
    return tree


def _parse_math_expr(cur: Cursor, min_prec: int) -> MathTree:
    return _parse_math_cont(cur, _parse_math_atom(cur), min_prec)


def _num_const(raw: str) -> MathTree:
    # integer literals stay python ints: int math must be exact
    # beyond 2^53 (ref query4:TestBigMathValue; math.go int64 arm)
    try:
        return MathTree(const=int(raw))
    except ValueError:
        return MathTree(const=float(raw))


def _parse_math_cont(cur: Cursor, left: MathTree,
                     min_prec: int) -> MathTree:
    while True:
        t = cur.peek()
        if t.kind == "number" and t.val.startswith("-") \
                and _MATH_PREC["-"] >= min_prec:
            # `f-2` lexes the literal as negative; after an operand it
            # is binary minus whose RHS STARTS with the positive
            # number — the RHS still binds tighter operators first
            # (f-2*3 == f-(2*3))
            cur.next()
            right = _parse_math_cont(cur, _num_const(t.val[1:]),
                                     _MATH_PREC["-"] + 1)
            left = MathTree(fn="-", children=[left, right])
            continue
        if t.kind == "op" and t.val in _MATH_PREC and _MATH_PREC[t.val] >= min_prec:
            cur.next()
            right = _parse_math_expr(cur, _MATH_PREC[t.val] + 1)
            left = MathTree(fn=t.val, children=[left, right])
        else:
            return left


def _parse_math_atom(cur: Cursor) -> MathTree:
    t = cur.next()
    if t.kind == "lparen":
        e = _parse_math_expr(cur, 0)
        cur.expect("rparen")
        return e
    if t.kind == "number":
        return _num_const(t.val)
    if t.kind == "name":
        if t.val in _MATH_FUNCS and cur.peek().kind == "lparen":
            cur.next()
            node = MathTree(fn=t.val)
            while not cur.accept("rparen"):
                node.children.append(_parse_math_expr(cur, 0))
                cur.accept("comma")
            return node
        if t.val == "val" and cur.peek().kind == "lparen":
            cur.next()
            v = cur.expect("name").val
            cur.expect("rparen")
            return MathTree(var=v)
        return MathTree(var=t.val)
    raise GQLError(f"line {t.line}: bad math expression at {t.val!r}")


# -- post-processing ---------------------------------------------------------


def _expand_fragments(gq: GraphQuery, fragments: dict, seen: set):
    out = []
    for child in gq.children:
        if child.attr.startswith("fragment/"):
            fname = child.attr.split("/", 1)[1]
            if fname in seen:
                raise GQLError(f"fragment cycle at {fname}")
            frag = fragments.get(fname)
            if frag is None:
                raise GQLError(f"missing fragment {fname}")
            _expand_fragments(frag, fragments, seen | {fname})
            out.extend(frag.children)
        else:
            _expand_fragments(child, fragments, seen)
            out.append(child)
    gq.children = out


def _collect_needs(gq: GraphQuery, res: ParsedResult):
    for vc in gq.needs_var:
        res.query_vars.append(vc.name)
    if gq.func:
        for vc in gq.func.needs_var:
            res.query_vars.append(vc.name)
    if gq.filter:
        _collect_filter_needs(gq.filter, res)
    for c in gq.children:
        _collect_needs(c, res)


def _collect_filter_needs(ft: FilterTree, res: ParsedResult):
    if ft.func:
        for vc in ft.func.needs_var:
            res.query_vars.append(vc.name)
    for c in ft.children:
        _collect_filter_needs(c, res)
