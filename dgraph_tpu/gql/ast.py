"""GraphQL± AST node types.

Semantic mirror of the reference's gql.GraphQuery / gql.Function /
gql.FilterTree (gql/parser.go:47,155,168) — same information content,
Python dataclasses instead of one large struct, and the planner-facing
fields (pagination, order) are typed instead of living in a string map.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

UID_VAR = 1
VALUE_VAR = 2
ANY_VAR = 0


@dataclass
class VarContext:
    """A variable this node consumes. Ref gql.VarContext (parser.go:139)."""

    name: str
    typ: int  # UID_VAR | VALUE_VAR | ANY_VAR


@dataclass
class Arg:
    """Function argument. Ref gql.Arg (parser.go:161)."""

    value: str
    is_value_var: bool = False   # val(x)
    is_graphql_var: bool = False  # $x


@dataclass
class Function:
    """A root/filter function call like eq(name, "x").
    Ref gql.Function (parser.go:168)."""

    name: str
    attr: str = ""
    lang: str = ""
    args: list[Arg] = field(default_factory=list)
    uids: list[int] = field(default_factory=list)
    needs_var: list[VarContext] = field(default_factory=list)
    is_count: bool = False      # eq(count(friend), 2)
    is_value_var: bool = False  # eq(val(v), 5)
    is_len_var: bool = False    # eq(len(v), 5)


@dataclass
class FilterTree:
    """Boolean combination of functions. Ref gql.FilterTree (parser.go:155)."""

    op: str = ""  # "and" | "or" | "not" | "" (leaf)
    children: list["FilterTree"] = field(default_factory=list)
    func: Optional[Function] = None


@dataclass
class Order:
    """One sort key. Ref pb.Order."""

    attr: str
    desc: bool = False
    lang: str = ""


@dataclass
class RecurseArgs:
    """@recurse(depth: N, loop: true). Ref gql.RecurseArgs (parser.go:92)."""

    depth: int = 0
    allow_loop: bool = False


@dataclass
class ShortestArgs:
    """shortest(from:, to:, numpaths:, depth:).
    Ref gql.ShortestPathArgs (parser.go:100)."""

    from_: Optional[Function] = None
    to: Optional[Function] = None
    numpaths: int = 1
    depth: int = 0
    minweight: float = float("-inf")
    maxweight: float = float("inf")


@dataclass
class GroupByAttr:
    attr: str
    alias: str = ""
    lang: str = ""


@dataclass
class MathTree:
    """Math expression tree. Ref gql.MathTree (math.go)."""

    fn: str = ""                 # operator or "" for leaf
    const: Optional[float] = None
    var: str = ""
    children: list["MathTree"] = field(default_factory=list)


@dataclass
class FacetParams:
    all_keys: bool = False
    keys: list[tuple[str, str]] = field(default_factory=list)  # (key, alias)


@dataclass
class GraphQuery:
    """One query block / nested predicate node.
    Ref gql.GraphQuery (gql/parser.go:47)."""

    attr: str = ""
    alias: str = ""
    langs: list[str] = field(default_factory=list)
    uids: list[int] = field(default_factory=list)
    func: Optional[Function] = None
    filter: Optional[FilterTree] = None
    order: list[Order] = field(default_factory=list)
    first: Optional[int] = None
    offset: int = 0
    after: int = 0
    children: list["GraphQuery"] = field(default_factory=list)
    is_count: bool = False
    is_internal: bool = False
    var: str = ""                       # `x as ...`
    needs_var: list[VarContext] = field(default_factory=list)
    expand: str = ""                    # expand(_all_) / expand(var)
    recurse: Optional[RecurseArgs] = None
    shortest: Optional[ShortestArgs] = None
    cascade: bool = False
    normalize: bool = False
    ignore_reflex: bool = False
    groupby: list[GroupByAttr] = field(default_factory=list)
    is_groupby: bool = False
    math: Optional[MathTree] = None
    agg_func: str = ""                  # min/max/sum/avg at value level
    agg_pred: str = ""                  # max(name): aggregate a
                                        # predicate (groupby only)
    facets: Optional[FacetParams] = None
    facets_filter: Optional[FilterTree] = None
    facet_var: dict = field(default_factory=dict)
    checkpwd_pwd: Optional[str] = None  # checkpwd(pred, "plain") field
    is_empty: bool = False              # var-only block with no func


@dataclass
class ParsedResult:
    """Ref gql.Result (parser.go:210)."""

    queries: list[GraphQuery] = field(default_factory=list)
    query_vars: list[str] = field(default_factory=list)
    # `schema {}` / `schema(pred: [..]) { fields }` introspection block
    # (ref gql.Parse handling of itemLeftCurl+schema, parser.go:524 →
    # Result.Schema): None = not requested; {"preds": [...], "fields":
    # [...]} with empty lists meaning "all"
    schema_request: Optional[dict] = None
    # document-level `@explain` flag: "" (off), "plan" (EXPLAIN) or
    # "analyze" (EXPLAIN ANALYZE). A request annotation — it rides in
    # extensions.explain and never changes execution or the data bytes
    explain: str = ""
