"""dgraph_tpu — a TPU-native distributed graph database framework.

A ground-up re-design of the capabilities of Dgraph (reference:
/root/reference, Go, v1.1.x) for TPU hardware:

- The query-execution data plane — posting-list decode (ref codec/codec.go),
  sorted-UID set algebra (ref algo/uidlist.go), multi-hop expansion
  (ref query/query.go ProcessGraph), BFS/recurse (ref query/recurse.go),
  shortest paths (ref query/shortest.go) and order-by/top-k
  (ref worker/sort.go) — runs as batched jit/vmap XLA kernels over padded
  sorted-UID tensors resident in HBM.
- The control plane — GraphQL± parsing, schema, MVCC transactions, UID/ts
  leases, replication — stays host-side, mirroring the reference's
  edgraph/gql/schema/posting/zero layering but with level-batched device
  calls instead of goroutine fan-out.

Package layout:
  ops/       device kernels: uidvec set algebra, delta codec, adjacency
             expansion, top-k, BFS/SSSP
  models/    data model: schema, scalar types, tokenizers, posting lists
  storage/   host-side MVCC key-value store, WAL, rollups
  gql/       GraphQL± lexer/parser -> AST
  query/     planner (SubGraph-equivalent), executor, JSON encoding
  engine/    single-process engine (Alpha-equivalent) + txn oracle
  cluster/   coordinator (Zero-equivalent), membership, distribution
  parallel/  device mesh, shardings, cross-shard collectives
  utils/     key codec, config, metrics
"""

__version__ = "0.1.0"
