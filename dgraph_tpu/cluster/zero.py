"""Zero state machine: replicated timestamps, uid leases, conflict
oracle and tablet map.

The reference's Zero keeps this state behind its own Raft quorum
(dgraph/cmd/zero/raft.go:619 applyProposal, assign.go:64 lease blocks,
oracle.go commit decisions, tablet.go:62 tablet claims). ZeroState is
that state machine extracted: every command is deterministic, so each
quorum member applies it identically and the proposer reads its local
apply result — no leader-only state.

Commands (payload = (op, args)):
  ("assign_ts",  (n,))                -> first ts of a block of n
  ("assign_uids",(n,))                -> first uid of a lease of n
  ("commit",     (start_ts, keys))    -> commit_ts, or 0 = conflict abort
                                         (idempotent: a decided txn
                                         returns its recorded outcome)
  ("txn_status", (start_ts,))         -> {"decided": bool, "commit_ts": n}
                                         — 2PC participants recover a
                                         crashed coordinator's decision
                                         (ref zero/oracle.go delta
                                         stream to alphas)
  ("abort_txn",  (start_ts,))         -> final decision for start_ts:
                                         records an abort unless the
                                         txn already committed (safe
                                         eviction of stale stages)
  ("tablet",     (pred, group))       -> owning group id (first claim wins)
  ("tablet_move_start", (pred, dst))  -> True once the tablet is marked
                                         read-only for the move
                                         (legacy one-shot path)
  ("move_request", (pred, dst[, nshards, shard]))
                                      -> enqueue a live move (or a
                                         hash-range split of `shard`)
                                         for the leader's driver; NO
                                         write fence yet
  ("move_phase", (pred, dst, phase[, snap_ts]))
                                      -> persist one phase transition
                                         (snapshotting -> catching_up
                                         -> fenced -> flipped); the
                                         fence entry/exit sets/clears
                                         the moving mark
  ("tablet_move_done", (pred, dst))   -> flips ownership + clears the
                                         moving mark (zero/tablet.go:62)
  ("tablet_size", (pred, bytes))      -> records a size report (the
                                         rebalancer's input,
                                         zero/tablet.go:180)
  ("tablet_heat", ({pred: (bytes, touches_delta)},))
                                      -> size + heat-EWMA report (the
                                         heat-driven rebalancer's load
                                         signal)
  ("connect", (key, want_group, raft_addr, client_addr, replicas))
                                      -> group assignment for a
                                         (re)connecting alpha: joins
                                         the least-replicated group
                                         under the replica target, or
                                         founds a new one
                                         (zero/zero.go:410 Connect)
  ("set_write_fence", (on,))          -> the CLUSTER-WIDE client-write
                                         fence (async replication:
                                         standbys boot fenced; a
                                         promotion fences the old
                                         primary). Replication applies
                                         bypass it — they land through
                                         the replicated-record path,
                                         not the ownership check.
  ("repl_phase", (phase,))            -> replication role transition:
                                         "" (normal primary) ->
                                         "standby" -> "promoting" ->
                                         "promoted" (now primary)
"""

from __future__ import annotations

from typing import Any


class ZeroState:
    def __init__(self):
        self.max_ts = 0
        self.next_uid = 1
        # conflict window: key fingerprint -> last commit_ts
        # (zero/oracle.go commits map)
        self.commits: dict[int, int] = {}
        self.commits_floor = 0
        # decided transactions: start_ts -> commit_ts (0 = aborted).
        # The 2PC decision record: participants and retrying
        # coordinators read the outcome here instead of re-deciding.
        # decided_floor marks the trim horizon — status of anything
        # below it is unknowable (participants keep such stages
        # pending rather than guess)
        self.decided: dict[int, int] = {}
        self.decided_floor = 0
        self.tablets: dict[str, int] = {}
        self.moving: dict[str, int] = {}   # pred -> destination group
        # zero-owned move ledger (ref zero/tablet.go:62 movetablet —
        # the LEADER drives moves; the replicated phase machine lets a
        # new leader resume or roll back an in-flight move from the
        # exact phase it died in):
        #   pred -> {"dst": group, "src": group,
        #            "phase": "snapshotting" | "catching_up" |
        #                     "fenced" | "flipped",
        #            "snap_ts": int,                # catch-up base
        #            "nshards": int, "shard": int|None}  # split moves
        # Writes fence ONLY in "fenced" (self.moving set on entry,
        # cleared on flip/unfence/abort); reads never fence.
        self.move_queue: dict[str, dict] = {}
        # hash-range split registry: pred -> {"owners": [group per
        # shard]} — shard i of an n-way split (n = len(owners)) serves
        # subjects with shard_of(uid, n) == i (cluster/shard.py)
        self.splits: dict[str, dict] = {}
        self.sizes: dict[str, int] = {}    # pred -> reported bytes
        # per-tablet heat: EWMA of the alphas' reported query-path
        # touch DELTAS (storage/tabstats.py `touches`) — the
        # rebalancer's load signal (applied through raft, so every
        # quorum member computes the identical value)
        self.heat: dict[str, float] = {}
        # alpha registry: key (raft "host:port") -> member record
        # (zero/zero.go membership state)
        self.alphas: dict[str, dict] = {}
        # cross-cluster async replication (cluster/replication.py):
        # write_fence refuses ALL client writes cluster-wide (standby
        # clusters; a fenced old primary after promotion); repl_phase
        # is the replicated role so a new zero leader resumes the
        # standby loop — or stays promoted — exactly where the old
        # one died
        self.write_fence = False
        self.repl_phase = ""

    # ------------------------------------------------------------- apply

    def apply(self, cmd: tuple) -> Any:
        op, args = cmd
        if op == "assign_ts":
            (n,) = args
            first = self.max_ts + 1
            self.max_ts += int(n)
            return first
        if op == "read_ts":
            # non-bumping read grant for watermark-bounded follower
            # reads: every FUTURE commit_ts is > max_ts by
            # construction, so the snapshot at max_ts is final — a
            # replica whose applied watermark reaches it can serve the
            # read without waiting for a commit that will never come
            # (a fresh assign_ts here would stall idle clusters: no
            # commit ever lands ON a read-only allocation)
            return self.max_ts
        if op == "assign_uids":
            (n,) = args
            first = self.next_uid
            self.next_uid += int(n)
            return first
        if op == "commit":
            start_ts, keys = args
            start_ts = int(start_ts)
            if start_ts in self.decided:  # retry of a decided txn
                return self.decided[start_ts]
            if start_ts < self.commits_floor:
                # the conflict entries this txn would have to check
                # against may have been trimmed: conservative ABORT
                # (the reference oracle likewise rejects txns older
                # than its purge point) — committing could silently
                # miss a write-write conflict
                self.decided[start_ts] = 0
                return 0
            for k in keys:
                if self.commits.get(int(k), 0) > start_ts:
                    self.decided[start_ts] = 0
                    return 0  # write-write conflict: abort
            self.max_ts += 1
            commit_ts = self.max_ts
            for k in keys:
                self.commits[int(k)] = commit_ts
            self.decided[start_ts] = commit_ts
            self._trim_decided()
            self._trim_commits()
            return commit_ts
        if op == "txn_status":
            (start_ts,) = args
            got = self.decided.get(int(start_ts))
            return {"decided": got is not None,
                    "commit_ts": got or 0,
                    # participants must treat ts below the trim floor
                    # as unknowable, never as implicitly aborted
                    "floor": self.decided_floor}
        if op == "abort_txn":
            (start_ts,) = args
            return self.decided.setdefault(int(start_ts), 0)
        if op == "tablet":
            pred, group = args
            if pred in self.splits:
                # a split predicate has no single owner: claiming it
                # whole would shadow the range routing. -1 = "routed
                # per shard" (no group passes an ownership check).
                return -1
            return self.tablets.setdefault(pred, int(group))
        if op == "bump_maxes":
            # bulk-booted alphas push their snapshot watermarks so
            # zero never leases a ts/uid below pre-loaded data (ref
            # bulk/loader.go:88 leasing from zero + zero/assign.go)
            max_ts, next_uid = args
            self.max_ts = max(self.max_ts, int(max_ts))
            self.next_uid = max(self.next_uid, int(next_uid))
            return {"max_ts": self.max_ts, "next_uid": self.next_uid}
        if op == "tablet_move_start":
            pred, dst = args
            if pred not in self.tablets or \
                    self.tablets[pred] == int(dst) or pred in self.moving:
                return False
            self.moving[pred] = int(dst)
            return True
        if op == "move_request":
            # zero-owned move: enqueues for the leader's driver thread
            # (serialized: one ledger entry per pred; concurrent movers
            # get False back). Writes are NOT fenced here — the source
            # keeps serving reads AND writes through snapshotting and
            # catch-up; the fence is the short "fenced" phase only.
            # args = (pred, dst) for a whole-tablet move, or
            # (pred, dst, nshards, shard) to split `shard` of an n-way
            # hash-range split onto dst.
            pred, dst = args[0], int(args[1])
            nshards = int(args[2]) if len(args) > 2 else 1
            shard = int(args[3]) if len(args) > 3 and \
                args[3] is not None else None
            if pred not in self.tablets or pred in self.moving \
                    or pred in self.move_queue or pred in self.splits:
                return False
            if self.tablets[pred] == dst:
                return False  # no-op move; a split NEEDS another group
            if shard is not None and not (0 <= shard < nshards
                                          and nshards > 1):
                return False
            # src is captured HERE: after the flip the tablet map
            # points at dst, and the driver still owes the drop/prune
            # on the ORIGINAL owner (a resumed leader must not lose it)
            self.move_queue[pred] = {
                "dst": dst, "src": self.tablets[pred],
                "phase": "snapshotting", "snap_ts": 0,
                "nshards": nshards, "shard": shard}
            return True
        if op == "move_phase":
            # one phase transition of the ledger's machine, persisted
            # through raft so a new zero leader resumes exactly here:
            #   snapshotting -> catching_up   (snapshot installed)
            #   catching_up  -> fenced        (lag under bound; SETS
            #                                  the single-predicate
            #                                  write fence)
            #   fenced       -> catching_up   (fence drain timed out:
            #                                  UNFENCE, writes resume)
            #   catching_up  -> snapshotting  (CDC floor overtook the
            #                                  base: re-snapshot)
            pred, dst, phase = args[0], int(args[1]), args[2]
            snap_ts = int(args[3]) if len(args) > 3 else 0
            mv = self.move_queue.get(pred)
            if mv is None or mv["dst"] != dst:
                return False
            legal = {("snapshotting", "catching_up"),
                     ("catching_up", "fenced"),
                     ("fenced", "catching_up"),
                     ("catching_up", "snapshotting"),
                     # a fence-drain discovering the destination lost
                     # its copy / the log truncated restarts from a
                     # fresh snapshot (and UNFENCES via the phase
                     # exit below) — without this edge the driver
                     # would wedge fenced forever
                     ("fenced", "snapshotting"),
                     # legacy pre-phase-machine ledger entries drive
                     # through the streaming path too
                     ("start", "catching_up"),
                     ("start", "snapshotting")}
            if (mv["phase"], phase) not in legal:
                return False
            mv["phase"] = phase
            if snap_ts:
                mv["snap_ts"] = snap_ts
            if phase == "fenced":
                self.moving[pred] = dst
            else:
                self.moving.pop(pred, None)
            return True
        if op == "tablet_move_done":
            pred, dst = args
            if self.moving.get(pred) != int(dst):
                return False
            mv = self.move_queue.get(pred)
            if mv is not None and mv.get("shard") is not None:
                # split flip: the predicate becomes an n-way hash-range
                # split — the moved shard serves from dst, every other
                # shard stays with the source (cluster/shard.py routing)
                owners = [mv["src"]] * int(mv["nshards"])
                owners[int(mv["shard"])] = int(dst)
                self.splits[pred] = {"owners": owners}
                self.tablets.pop(pred, None)
            else:
                self.tablets[pred] = int(dst)
            del self.moving[pred]
            if mv is not None:
                # ownership flipped; the driver still owes the source
                # drop/prune — keep the ledger entry so a NEW leader
                # redoes it after a crash (both are idempotent)
                mv["phase"] = "flipped"
            return True
        if op == "tablet_move_abort":
            pred, dst = args
            if pred not in self.move_queue \
                    or self.move_queue[pred]["dst"] != int(dst):
                return False
            if self.move_queue[pred]["phase"] == "flipped":
                # post-flip the DESTINATION owns the only routed copy:
                # aborting now could only orphan or delete owned data
                # — the driver finishes the source drop instead
                return False
            self.moving.pop(pred, None)  # unfence if fenced
            self.move_queue.pop(pred, None)
            return True
        if op == "move_finish":
            (pred,) = args
            self.move_queue.pop(pred, None)
            return True
        if op == "tablet_size":
            pred, nbytes = args
            self.sizes[pred] = int(nbytes)
            return True
        if op == "tablet_sizes":
            (batch,) = args
            for pred, nbytes in batch.items():
                self.sizes[pred] = int(nbytes)
            return True
        if op == "tablet_heat":
            # one leader's periodic report: {pred: (bytes,
            # touches_delta)} — touch deltas since ITS last report.
            # Heat folds as an EWMA (identical on every quorum member:
            # the fold runs at raft apply); decay-on-report keeps a
            # cooled tablet's heat falling even when its group reports
            # zero deltas.
            (batch,) = args
            for pred, (nbytes, dt) in batch.items():
                # a SPLIT predicate's owners each report only their
                # shard's bytes/touches: scale to a whole-predicate
                # estimate before folding, or the shared EWMA would
                # converge to a per-shard value and the planner would
                # undercount split load ~owners-fold (piling more
                # tablets onto the groups the split was relieving)
                scale = len(self.splits[pred]["owners"]) \
                    if pred in self.splits else 1
                self.sizes[pred] = int(nbytes) * scale
                self.heat[pred] = round(
                    0.5 * self.heat.get(pred, 0.0)
                    + 0.5 * float(dt) * scale, 3)
            return True
        if op == "set_write_fence":
            (on,) = args
            self.write_fence = bool(on)
            return self.write_fence
        if op == "repl_phase":
            (phase,) = args
            if phase not in ("", "standby", "promoting", "promoted"):
                return False
            self.repl_phase = str(phase)
            return True
        if op == "connect":
            key, want_group, want_id, raft_addr, client_addr, \
                replicas = args[:6]
            # 7th arg (optional, newer alphas): non-voting learner —
            # registered for routing/membership but excluded from
            # replica-count placement, so a read replica never
            # satisfies a group's WRITE-quorum replica target
            learner = bool(args[6]) if len(args) > 6 else False
            prev = self.alphas.get(key)
            if prev is not None:
                # idempotent reconnect (restart at the same addr):
                # same assignment back, addresses refreshed from args
                gid = prev["group"]
                prev["raft"] = tuple(raft_addr)
                prev["client"] = tuple(client_addr)
                if learner:
                    prev["learner"] = True
            else:
                counts: dict[int, int] = {}
                for rec in self.alphas.values():
                    if rec.get("learner"):
                        continue  # learners don't count as replicas
                    counts[rec["group"]] = counts.get(rec["group"], 0) + 1
                gid = int(want_group)
                if gid <= 0:
                    # least-replicated group under the target, else a
                    # fresh group (zero.go:410-560 replica-count join).
                    # A learner joins the least-LOADED existing group
                    # instead of founding one: a group of only
                    # learners could never elect a leader.
                    if learner and counts:
                        under = sorted((n, g)
                                       for g, n in counts.items())
                        gid = under[0][1]
                    else:
                        under = [(n, g)
                                 for g, n in sorted(counts.items())
                                 if n < int(replicas)]
                        gid = min(under)[1] if under else \
                            (max(counts) + 1 if counts else 1)
                if int(want_id) > 0:
                    # explicit-group member registering its REAL raft
                    # id: a record in this group with the same id but
                    # a different key is a ghost of this node's
                    # previous incarnation (restarted on new ports) —
                    # replace it, never invent a new id
                    nid = int(want_id)
                    for k, rec in list(self.alphas.items()):
                        if rec["group"] == gid and rec["id"] == nid:
                            del self.alphas[k]
                else:
                    used = {rec["id"] for rec in self.alphas.values()
                            if rec["group"] == gid}
                    nid = max(used, default=0) + 1
                self.alphas[key] = {
                    "group": gid, "id": nid,
                    "raft": tuple(raft_addr),
                    "client": tuple(client_addr)}
                if learner:
                    self.alphas[key]["learner"] = True
            members = {rec["id"]: {"raft": rec["raft"],
                                   "client": rec["client"],
                                   "learner": bool(rec.get("learner"))}
                       for rec in self.alphas.values()
                       if rec["group"] == gid}
            return {"group": gid, "id": self.alphas[key]["id"],
                    "members": members}
        raise ValueError(f"unknown zero command {op!r}")

    def _trim_commits(self):
        """Bound the conflict window the same way: an entry only
        matters while a txn with start_ts below its commit_ts can
        still try to commit, and anything 10M ts behind max_ts is far
        past every stage TTL. Skipped while nothing is trimmable so
        commits never pay an O(window) rebuild for free."""
        if len(self.commits) > 131072:
            floor = self.max_ts - 10_000_000
            if floor - self.commits_floor < 1_000_000:
                # rebuild only when the floor has advanced a real
                # stride — with >131k live in-window keys an every-
                # commit rebuild would evict ~nothing at O(window) cost
                return
            self.commits = {k: v for k, v in self.commits.items()
                            if v >= floor}
            self.commits_floor = floor

    def _trim_decided(self):
        """Bound the decision registry: deterministic trim (applied
        identically on every quorum member) keeping a generous window
        behind max_ts. The 10M-ts window dwarfs the participants' 300s
        stage TTL unless zero sustains >33k ts-ops/s while a
        participant stays partitioned the whole time; even then the
        recorded floor makes participants keep (not mis-abort) stages
        whose decision was trimmed."""
        if len(self.decided) > 131072:
            floor = self.max_ts - 10_000_000
            if floor - self.decided_floor < 1_000_000:
                # rebuild only when the floor has advanced a real
                # stride — an every-commit rebuild over >131k retained
                # decisions would evict ~nothing at O(window) cost.
                # Growth stays bounded by ts volume between strides.
                return
            self.decided = {ts: c for ts, c in self.decided.items()
                            if ts >= floor}
            self.decided_floor = floor

    # --------------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        return {"max_ts": self.max_ts, "next_uid": self.next_uid,
                "commits": dict(self.commits),
                "decided": dict(self.decided),
                "decided_floor": self.decided_floor,
                "commits_floor": self.commits_floor,
                "tablets": dict(self.tablets),
                "moving": dict(self.moving),
                "move_queue": {k: dict(v)
                               for k, v in self.move_queue.items()},
                "splits": {k: dict(v) for k, v in self.splits.items()},
                "sizes": dict(self.sizes),
                "heat": dict(self.heat),
                "write_fence": self.write_fence,
                "repl_phase": self.repl_phase,
                "alphas": {k: dict(v) for k, v in self.alphas.items()}}

    @classmethod
    def from_snapshot(cls, snap: dict) -> "ZeroState":
        st = cls()
        st.max_ts = snap["max_ts"]
        st.next_uid = snap["next_uid"]
        st.commits = dict(snap["commits"])
        st.decided = dict(snap.get("decided", {}))
        st.decided_floor = snap.get("decided_floor", 0)
        st.commits_floor = snap.get("commits_floor", 0)
        st.tablets = dict(snap["tablets"])
        st.moving = dict(snap.get("moving", {}))
        st.move_queue = {k: dict(v) for k, v
                         in snap.get("move_queue", {}).items()}
        st.splits = {k: dict(v)
                     for k, v in snap.get("splits", {}).items()}
        st.sizes = dict(snap.get("sizes", {}))
        st.heat = dict(snap.get("heat", {}))
        st.write_fence = bool(snap.get("write_fence", False))
        st.repl_phase = str(snap.get("repl_phase", ""))
        st.alphas = {k: dict(v)
                     for k, v in snap.get("alphas", {}).items()}
        return st
