"""Heat-driven rebalance planning: pure policy over Zero's stats.

The reference rebalances by tablet SIZE every 8 minutes
(zero/tablet.go:62 rebalanceTablets / chooseTablet); size alone cannot
see the million-user failure mode — a small-but-viral predicate pins
its group's CPU while the byte spread looks balanced. This planner
weighs tablets by the HEAT EWMA Zero folds from the alphas' query-path
touch deltas (zero.py "tablet_heat"), falling back to bytes when the
cluster is idle, and adds the second tool size-rebalancing lacks
entirely: when one predicate IS the imbalance (moving it whole would
just relocate the hot spot), it proposes a hash-range SPLIT instead,
so the load divides across groups.

Pure functions over a plain state view — ZeroServer's leader loop
feeds it `ZeroState` fields and proposes the returned request; unit
tests feed it dicts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class RebalanceConfig:
    # hysteresis band: act only when the heaviest group carries more
    # than `band`x the lightest's load AND the absolute spread clears
    # `min_spread` (tiny clusters must not thrash over noise)
    band: float = 1.4
    min_spread: float = 64.0
    # a predicate whose weight exceeds `split_frac` of its group's
    # load AND `split_heat` absolute heat splits 2-way instead of
    # moving whole. split_heat <= 0 disables splitting.
    split_frac: float = 0.5
    split_heat: float = 0.0
    split_shards: int = 2
    # never auto-move these predicates (operator pin,
    # --rebalance-pin): the knob for colocation constraints the
    # planner cannot see — e.g. a vector predicate and the attributes
    # its similar_to queries select (cross-group vector search is not
    # supported), or a bundle an SLA wants welded to local reads
    pinned: frozenset = frozenset()


@dataclass
class RebalancePlan:
    kind: str            # "move" | "split"
    pred: str
    dst: int
    nshards: int = 1
    shard: Optional[int] = None

    def args(self) -> tuple:
        """The ("move_request", args) payload (cluster/zero.py)."""
        if self.kind == "split":
            return (self.pred, self.dst, self.nshards, self.shard)
        return (self.pred, self.dst)


def tablet_weights(view: dict) -> dict[str, float]:
    """Per-tablet load weight: heat EWMA when the cluster shows any
    (the signal that sees viral predicates), bytes otherwise (the
    reference's size heuristic, the right call for an idle cluster
    being packed)."""
    heat = view.get("heat", {})
    sizes = view.get("sizes", {})
    preds = set(view.get("tablets", ())) | set(view.get("splits", ()))
    if any(heat.get(p, 0.0) > 0.0 for p in preds):
        return {p: float(heat.get(p, 0.0)) for p in preds}
    return {p: float(sizes.get(p, 0)) for p in preds}


def group_loads(view: dict, weights: dict[str, float]) -> dict[int, float]:
    """Group -> summed tablet weight. A split predicate contributes
    one even share per shard to each shard's owner (the per-shard heat
    is not tracked separately; even division is the unbiased prior)."""
    loads = {int(g): 0.0 for g in view.get("groups", ())}
    for pred, gid in view.get("tablets", {}).items():
        if pred.startswith("dgraph."):
            continue
        loads[int(gid)] = loads.get(int(gid), 0.0) \
            + weights.get(pred, 0.0)
    for pred, ent in view.get("splits", {}).items():
        owners = ent["owners"]
        share = weights.get(pred, 0.0) / max(1, len(owners))
        for gid in owners:
            loads[int(gid)] = loads.get(int(gid), 0.0) + share
    return loads


def plan_rebalance(view: dict,
                   cfg: Optional[RebalanceConfig] = None
                   ) -> Optional[RebalancePlan]:
    """At most ONE proposed action per call (the ledger executes moves
    serially; one step per tick keeps a bad heuristic from thrashing).
    None = balanced within the hysteresis band, or nothing movable."""
    cfg = cfg or RebalanceConfig()
    if view.get("moving") or len(view.get("groups", ())) < 2:
        return None
    weights = tablet_weights(view)
    loads = group_loads(view, weights)
    if len(loads) < 2:
        return None
    heavy = max(sorted(loads), key=lambda g: loads[g])
    light = min(sorted(loads), key=lambda g: loads[g])
    spread = loads[heavy] - loads[light]
    if spread < cfg.min_spread or \
            loads[heavy] <= cfg.band * max(loads[light], 1e-9):
        return None
    frozen = set(cfg.pinned) | set(view.get("frozen", ()))
    movable = sorted(p for p, g in view.get("tablets", {}).items()
                     if int(g) == heavy and not p.startswith("dgraph.")
                     and p not in frozen)
    if not movable:
        return None
    # the dominant-predicate test first: when one tablet IS the load,
    # moving it whole only mirrors the imbalance — split it instead
    hot = max(movable, key=lambda p: (weights.get(p, 0.0), p))
    hot_w = weights.get(hot, 0.0)
    heat = view.get("heat", {})
    if cfg.split_heat > 0 and heat.get(hot, 0.0) >= cfg.split_heat \
            and hot_w > cfg.split_frac * loads[heavy]:
        return RebalancePlan("split", hot, light,
                             nshards=cfg.split_shards,
                             shard=cfg.split_shards - 1)
    # otherwise the reference's chooseTablet rule, heat-weighted: the
    # SMALLEST candidate whose move strictly shrinks the pair's
    # spread. Smallest-first is deliberate, twice over: each move's
    # blast radius (stream bytes, fence, routing churn, queries that
    # temporarily federate when one predicate of a colocated bundle
    # moves ahead of its siblings) stays minimal, and the dominant
    # hot tablet stays put unless nothing smaller can help — at which
    # point the SPLIT above is the right tool, not a whole-tablet
    # move that merely relocates the hot spot.
    for pred in sorted(movable,
                       key=lambda p: (weights.get(p, 0.0), p)):
        w = weights.get(pred, 0.0)
        if abs((loads[heavy] - w) - (loads[light] + w)) < spread:
            return RebalancePlan("move", pred, light)
    return None
