"""Cluster client: leader-following RPC over the wire protocol.

The reference's clients (dgo) dial any Alpha and gRPC routes writes to
the group leader internally; our server instead answers
{"ok": False, "leader": id} and the client re-dials — same effect, one
hop visible. Retries cover elections in progress and nodes that just
died (conn/pool.go reconnect behavior).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Optional

from dgraph_tpu import wire


class ClusterClient:
    """Talks to an Alpha group or a Zero quorum (same protocol)."""

    # seconds a node stays demoted after a connection-level failure —
    # the client-side analogue of the reference's heartbeat health
    # gating (conn/pool.go:227 MonitorHealth marks pools unhealthy;
    # processWithBackupRequest avoids sick replicas)
    UNHEALTHY_S = 1.0

    def __init__(self, addrs: dict[int, tuple[str, int]],
                 timeout: float = 10.0):
        self.addrs = dict(addrs)
        self.timeout = timeout
        self._conns: dict[int, socket.socket] = {}
        self._preferred: Optional[int] = None
        self._down: dict[int, float] = {}  # node -> demoted-until
        self._lock = threading.Lock()

    # ------------------------------------------------------------ plumbing

    def _conn(self, node: int) -> Optional[socket.socket]:
        sock = self._conns.get(node)
        if sock is not None:
            return sock
        try:
            # connect budget never exceeds the client's deadline: a
            # SYN-blackholed peer must not eat a 2s connect timeout on
            # a 150ms-budget timestamp client (raft lock is held)
            sock = socket.create_connection(
                self.addrs[node], timeout=min(2.0, self.timeout))
            sock.settimeout(self.timeout)
        except OSError:
            return None
        self._conns[node] = sock
        return sock

    def _drop(self, node: int):
        sock = self._conns.pop(node, None)
        if sock is not None:
            sock.close()

    def _rpc_once(self, node: int, req: dict) -> Optional[dict]:
        sock = self._conn(node)
        if sock is None:
            self._down[node] = time.monotonic() + self.UNHEALTHY_S
            return None
        try:
            wire.write_frame(sock, wire.dumps(req))
            resp = wire.loads(wire.read_frame(sock))
            self._down.pop(node, None)
            return resp
        except (OSError, EOFError, wire.WireError):
            self._drop(node)
            self._down[node] = time.monotonic() + self.UNHEALTHY_S
            return None

    def request(self, req: dict, deadline_s: Optional[float] = None) -> dict:
        """Route to the leader, following hints and retrying through
        elections until the deadline."""
        deadline = time.monotonic() + (deadline_s or self.timeout)
        with self._lock:
            last_err = "unreachable"
            while time.monotonic() < deadline:
                order = [n for n in
                         ([self._preferred] + sorted(self.addrs))
                         if n is not None]
                # recently failed nodes go LAST, not skipped — if every
                # replica is demoted they are all still tried
                now = time.monotonic()
                order = sorted(order,
                               key=lambda n: self._down.get(n, 0) > now)
                seen = set()
                for node in order:
                    if node in seen or node not in self.addrs:
                        continue
                    seen.add(node)
                    resp = self._rpc_once(node, req)
                    if resp is None:
                        continue
                    if resp.get("ok"):
                        self._preferred = node
                        return resp
                    if resp.get("error") == "not leader":
                        hint = resp.get("leader")
                        if hint is not None and hint != node \
                                and hint in self.addrs:
                            self._preferred = hint
                            hinted = self._rpc_once(hint, req)
                            if hinted is not None and hinted.get("ok"):
                                return hinted
                        continue
                    return resp  # real application error: surface it
                last_err = "no leader reachable"
                time.sleep(0.1)
            return {"ok": False, "error": last_err}

    def close(self):
        with self._lock:
            for sock in self._conns.values():
                sock.close()
            self._conns.clear()

    # ------------------------------------------------------- alpha surface

    def query(self, q: str, variables: Optional[dict] = None,
              hedge_s: Optional[float] = None,
              read_ts: Optional[int] = None) -> dict:
        """Snapshot read from any replica. With hedge_s set, a backup
        request fires at a second replica if the first hasn't answered
        within the delay and the first response wins — the reference's
        processWithBackupRequest (worker/task.go:66) tail-latency
        defense."""
        req = {"op": "query", "q": q, "vars": variables}
        if read_ts is not None:
            req["read_ts"] = read_ts
            if hedge_s is not None:
                # pinned reads are leader-only; the hedge path fires at
                # arbitrary replicas with no leader rerouting
                raise ValueError(
                    "read_ts and hedge_s cannot be combined")
        if hedge_s is not None and len(self.addrs) > 1:
            return self._unwrap(self._hedged(req, hedge_s))
        return self._unwrap(self.request(req))

    def _hedged(self, req: dict, hedge_s: float) -> dict:
        """Fire at the preferred replica; after hedge_s with no answer,
        race a second replica on a FRESH connection (the pooled conns
        stay owned by the main path). First non-error response wins."""
        import queue

        with self._lock:
            now = time.monotonic()
            healthy = [n for n in sorted(self.addrs)
                       if self._down.get(n, 0) <= now]
            pool = healthy or sorted(self.addrs)
            first = self._preferred if self._preferred in pool \
                else pool[0]
        others = [n for n in sorted(self.addrs) if n != first]
        others = sorted(others,
                        key=lambda n: self._down.get(n, 0) > now)
        results: queue.Queue = queue.Queue()

        def attempt(node):
            try:
                sock = socket.create_connection(self.addrs[node],
                                                timeout=2.0)
                sock.settimeout(self.timeout)
                try:
                    wire.write_frame(sock, wire.dumps(req))
                    results.put(wire.loads(wire.read_frame(sock)))
                finally:
                    sock.close()
            except (OSError, EOFError, wire.WireError):
                results.put(None)

        threads = [threading.Thread(target=attempt, args=(first,),
                                    daemon=True)]
        threads[0].start()
        failures = 0
        try:
            got = results.get(timeout=hedge_s)
            if got is not None:
                return got  # ok or a real application error: surface it
            failures += 1   # connection-level failure
        except queue.Empty:
            pass
        # primary is slow/dead: hedge to a backup replica
        threads.append(threading.Thread(target=attempt, args=(others[0],),
                                        daemon=True))
        threads[1].start()
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline and failures < len(threads):
            try:
                got = results.get(timeout=max(
                    0.01, deadline - time.monotonic()))
            except queue.Empty:
                break
            if got is not None:
                return got
            failures += 1
        # both raced attempts failed to CONNECT: fall back to the
        # routed retry path
        return self.request(req)

    def mutate(self, **kw) -> dict:
        return self._unwrap(self.request({"op": "mutate", "kw": kw}))

    # dgo-style interactive txns: the group leader stages; commit
    # replicates (a leader change aborts open txns — retry)
    def txn_mutate(self, start_ts: int = 0, **kw) -> dict:
        kw["commit_now"] = False
        if start_ts:
            kw["start_ts"] = start_ts
        return self._unwrap(self.request({"op": "mutate", "kw": kw}))

    def txn_commit(self, start_ts: int, abort: bool = False) -> dict:
        return self._unwrap(self.request(
            {"op": "commit",
             "params": {"startTs": str(start_ts),
                        "abort": "true" if abort else "false"}}))

    def alter(self, schema_text: str = "", **kw) -> dict:
        kw["schema_text"] = schema_text
        return self._unwrap(self.request({"op": "alter", "kw": kw}))

    def members(self) -> dict:
        return self._unwrap(self.request({"op": "members"}))

    def conf_change(self, action: str, node: int,
                    addr: Optional[tuple[str, int]] = None) -> dict:
        """Add/remove a raft group member (ref conn/raft_server.go
        JoinCluster; zero /removeNode). After an add, call add_node()
        so this client can reach the new member too."""
        req = {"op": "conf_change", "action": action, "node": node}
        if addr is not None:
            req["addr"] = tuple(addr)
        return self._unwrap(self.request(req))

    def add_node(self, node: int, addr: tuple[str, int]):
        with self._lock:
            self.addrs[node] = tuple(addr)

    def remove_node(self, node: int):
        with self._lock:
            self.addrs.pop(node, None)
            self._drop(node)
            if self._preferred == node:
                self._preferred = None

    def status(self, node: Optional[int] = None) -> dict:
        if node is not None:
            with self._lock:
                resp = self._rpc_once(node, {"op": "status"})
            if resp is None:
                raise ConnectionError(f"node {node} unreachable")
            return resp["result"]
        return self._unwrap(self.request({"op": "status"}))

    # -------------------------------------------------------- zero surface

    def assign_ts(self, n: int = 1) -> int:
        return self._unwrap(self.request(
            {"op": "assign_ts", "args": (n,)}))

    def assign_uids(self, n: int) -> int:
        return self._unwrap(self.request(
            {"op": "assign_uids", "args": (n,)}))

    def commit(self, start_ts: int, keys: list[int]) -> int:
        return self._unwrap(self.request(
            {"op": "commit", "args": (start_ts, list(keys))}))

    def tablet(self, pred: str, group: int) -> int:
        return self._unwrap(self.request(
            {"op": "tablet", "args": (pred, group)}))

    @staticmethod
    def _unwrap(resp: dict) -> Any:
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "rpc failed"))
        return resp["result"]
