"""Cluster client: leader-following RPC over the wire protocol.

The reference's clients (dgo) dial any Alpha and gRPC routes writes to
the group leader internally; our server instead answers
{"ok": False, "leader": id} and the client re-dials — same effect, one
hop visible. Retries cover elections in progress and nodes that just
died (conn/pool.go reconnect behavior).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Optional

import random

from dgraph_tpu import wire
from dgraph_tpu.utils import netfault, tracing
from dgraph_tpu.utils.reqctx import Cancelled, DeadlineExceeded, Overloaded

# wire `aborted` field -> the typed error the serving node raised, so
# a coordinator's retry loop (or the HTTP edge's 408/499/429 mapping)
# sees cancellation as cancellation, not a generic RuntimeError
_ABORT_TYPES = {"DeadlineExceeded": DeadlineExceeded,
                "Cancelled": Cancelled,
                "Overloaded": Overloaded}


class ClusterClient:
    """Talks to an Alpha group or a Zero quorum (same protocol)."""

    # seconds a node stays demoted after a connection-level failure —
    # the client-side analogue of the reference's heartbeat health
    # gating (conn/pool.go:227 MonitorHealth marks pools unhealthy;
    # processWithBackupRequest avoids sick replicas)
    UNHEALTHY_S = 1.0

    # bounded-jitter backoff between full routing passes when no node
    # answered (partition, election in progress): starts near-instant
    # so a quick election costs one cheap retry, doubles toward the
    # cap so a PARTITIONED client stops hammering dead links, and the
    # jitter de-synchronizes the reconnect stampede when the partition
    # heals (every waiting client would otherwise redial in lockstep).
    # The chaos harness surfaced the fixed 0.1s sleep this replaces:
    # under a 30s-timeout client it burned a full routing pass — dials
    # included — every 100ms for the whole partition.
    BACKOFF_BASE_S = 0.02
    BACKOFF_CAP_S = 0.5

    def __init__(self, addrs: dict[int, tuple[str, int]],
                 timeout: float = 10.0):
        self.addrs = dict(addrs)
        self.timeout = timeout
        self._conns: dict[int, socket.socket] = {}
        self._preferred: Optional[int] = None
        self._down: dict[int, float] = {}  # node -> demoted-until
        # `_lock` guards ONLY the routing state (addrs/_preferred/
        # _down/_conns/_mus dict shape) and is never held across
        # socket I/O: one caller stuck on a sick peer must not
        # serialize every other caller's routing. Per-node `_mus`
        # mutexes serialize the frame write/read pair on the ONE
        # pooled request/response connection per peer.
        self._lock = threading.Lock()
        self._mus: dict[int, threading.Lock] = {}
        self._closed = False

    # ------------------------------------------------------------ plumbing

    def _node_mu(self, node: int) -> threading.Lock:
        with self._lock:
            mu = self._mus.get(node)
            if mu is None:
                mu = self._mus[node] = threading.Lock()
            return mu

    def _drop(self, node: int,
              sock: Optional[socket.socket] = None) -> bool:
        """Drop a failed pooled conn. With `sock` given, un-pool only
        if THAT socket is still the pooled one — an error surfacing on
        a stale handle must not destroy a healthy replacement another
        thread just dialed. Returns whether `sock` was still current
        (a stale failure says nothing about the node's health)."""
        with self._lock:
            cur = self._conns.get(node)
            current = cur is not None and (sock is None or cur is sock)
            if current:
                del self._conns[node]
            else:
                cur = None
        if cur is not None:
            cur.close()
        if sock is not None and sock is not cur:
            sock.close()  # already un-pooled; close our failed handle
        return current

    def _mark_down(self, node: int):
        with self._lock:
            self._down[node] = time.monotonic() + self.UNHEALTHY_S

    def _rpc_once(self, node: int, req: dict,
                  timeout: Optional[float] = None) -> Optional[dict]:
        """One framed RPC. `timeout` caps THIS attempt's socket waits
        (a caller deadline must bound blocking reads, not just the
        between-attempts loop check); the pooled socket's default
        timeout is restored on success, and a timed-out socket is
        dropped by the except path anyway.

        Locking: the pooled conn is dialed OUTSIDE any lock and
        inserted race-checked (transport.py's DG04 pattern — a 2s
        connect timeout to one dead peer must not block routing to
        healthy ones), then the per-node mutex serializes exactly the
        write+read pair so concurrent requests to one peer cannot
        interleave frames."""
        with self._lock:
            sock = self._conns.get(node)
            addr = self.addrs.get(node)
        if addr is None:
            return None
        if netfault.armed() \
                and netfault.act(addr, can_dup=False) == netfault.DROP:
            # the fault plane cut this link (utils/netfault.py): behave
            # exactly like a refused dial / reset connection — drop the
            # pooled socket, demote the node, let the routing loop try
            # the other replicas. Client->server partitions and every
            # server-side outbound RPC (alpha->zero ts, federated
            # tasks, 2PC stage/finalize) flow through here.
            if sock is not None:
                self._drop(node, sock)
            self._mark_down(node)
            return None
        if sock is None:
            # connect budget never exceeds the client's deadline: a
            # SYN-blackholed peer must not eat a 2s connect timeout
            # on a 150ms-budget timestamp client
            budget = self.timeout if timeout is None \
                else min(self.timeout, timeout)
            try:
                fresh = socket.create_connection(
                    addr, timeout=min(2.0, budget))
                fresh.settimeout(self.timeout)
            except OSError:
                self._mark_down(node)
                return None
            with self._lock:
                if self._closed:
                    # a racing close() already swept the pool; do not
                    # leak a fresh conn into a dead client
                    cur = None
                elif (cur := self._conns.get(node)) is None:
                    self._conns[node] = fresh
                    cur = fresh
                sock = cur
            if sock is not fresh:
                fresh.close()
            if sock is None:
                return None
        try:
            with self._node_mu(node):
                if timeout is not None:
                    sock.settimeout(
                        max(0.001, min(self.timeout, timeout)))
                wire.write_frame(sock, wire.dumps(req))
                resp = wire.loads(wire.read_frame(sock))
                if timeout is not None:
                    sock.settimeout(self.timeout)
            with self._lock:
                self._down.pop(node, None)
            return resp
        except socket.timeout:
            current = self._drop(node, sock)
            if current and (timeout is None
                            or timeout >= self.timeout):
                # a FULL-budget timeout says the node is sick; one cut
                # short by the caller's nearly-spent deadline says
                # nothing — demoting on it would poison the health
                # cache for every other user of this client
                self._mark_down(node)
            return None
        except (OSError, EOFError, wire.WireError):
            if self._drop(node, sock):
                self._mark_down(node)
            return None

    def request(self, req: dict, deadline_s: Optional[float] = None) -> dict:
        """Route to the leader, following hints and retrying through
        elections until the deadline. When the calling context is
        inside a trace (tracing.bind / an open span), the RPC records
        an `rpc.send` span and ships `trace_id`/`parent_span` on the
        wire so the serving node's spans join the originating trace
        (ref worker/task.go forwarding the request context)."""
        if tracing.current() is None:
            return self._request(req, deadline_s)
        with tracing.span("rpc.send", op=str(req.get("op", ""))):
            return self._request(self._traced(req), deadline_s)

    @staticmethod
    def _traced(req: dict) -> dict:
        """Copy of `req` carrying the active trace context: the remote
        `rpc.recv` span parents under OUR innermost span (here: the
        rpc.send span the caller just opened)."""
        cur = tracing.current()
        if cur is None:
            return req
        req = dict(req)
        req.setdefault("trace_id", cur[0])
        req["parent_span"] = cur[1]
        return req

    def _request(self, req: dict,
                 deadline_s: Optional[float] = None) -> dict:
        # an EXHAUSTED budget (0.0) must fail fast, not silently widen
        # to the default timeout — 0.0 is falsy but meaningful
        deadline = time.monotonic() + (
            self.timeout if deadline_s is None else deadline_s)
        # with an explicit budget, every attempt's SOCKET waits are
        # capped by what remains — a peer that accepts then stalls
        # mid-response must not hold an expired caller for the pooled
        # default timeout
        bounded = deadline_s is not None

        def attempt_timeout():
            return max(0.001, deadline - time.monotonic()) \
                if bounded else None

        last_err = "unreachable"
        passes = 0
        while time.monotonic() < deadline:
            # snapshot the routing state under the lock, then do every
            # RPC with NO lock held (the dial-outside-lock pattern: a
            # caller routing through a sick peer, or backing off, must
            # never serialize concurrent callers). Each pass
            # recomputes the candidate order from the CURRENT
            # _preferred/_down/addrs state, which may have moved.
            with self._lock:
                order = [n for n in
                         ([self._preferred] + sorted(self.addrs))
                         if n is not None]
                # recently failed nodes go LAST, not skipped — if every
                # replica is demoted they are all still tried
                now = time.monotonic()
                order = sorted(order,
                               key=lambda n: self._down.get(n, 0) > now)
                known = set(self.addrs)
            seen: set[int] = set()
            for node in order:
                if node in seen or node not in known:
                    continue
                if time.monotonic() >= deadline:
                    break
                seen.add(node)
                resp = self._rpc_once(node, req,
                                      timeout=attempt_timeout())
                if resp is None:
                    continue
                if resp.get("ok"):
                    with self._lock:
                        self._preferred = node
                    return resp
                if resp.get("error") == "not leader":
                    hint = resp.get("leader")
                    with self._lock:
                        follow = (hint is not None and hint != node
                                  and hint in self.addrs)
                        if follow:
                            self._preferred = hint
                    if follow and time.monotonic() < deadline:
                        hinted = self._rpc_once(
                            hint, req, timeout=attempt_timeout())
                        if hinted is not None and hinted.get("ok"):
                            return hinted
                    continue
                return resp  # real application error: surface it
            last_err = "no leader reachable"
            # bounded-jitter exponential backoff, never past the
            # caller's deadline (an expired budget exits the loop and
            # surfaces TYPED as DeadlineExceeded via deadline_expired)
            time.sleep(min(self._backoff_s(passes),
                           max(0.0, deadline - time.monotonic())))
            passes += 1
        # with a caller-supplied budget this is EXPIRY, not a
        # generic routing failure: the marker lets _unwrap raise
        # DeadlineExceeded so the HTTP edge answers 408 retryable
        # instead of 500 (elections in progress eat exactly this
        # path)
        return {"ok": False, "error": last_err,
                "deadline_expired": bounded}

    @classmethod
    def _backoff_s(cls, passes: int,
                   rng: random.Random = random) -> float:
        """Sleep before routing pass `passes+1`: BASE * 2^passes
        capped at CAP, scaled by uniform[0.5, 1.0) jitter. Pure (given
        an rng) so the bound is testable: always > 0, never above
        CAP."""
        step = min(cls.BACKOFF_CAP_S,
                   cls.BACKOFF_BASE_S * (1 << min(passes, 16)))
        return step * (0.5 + rng.random() * 0.5)

    def close(self):
        with self._lock:
            self._closed = True
            socks = list(self._conns.values())
            self._conns.clear()
        for sock in socks:
            sock.close()

    # ------------------------------------------------------- alpha surface

    def query(self, q: str, variables: Optional[dict] = None,
              hedge_s: Optional[float] = None,
              read_ts: Optional[int] = None,
              deadline_ms: Optional[int] = None,
              best_effort: bool = False,
              tenant: str = "") -> dict:
        """Snapshot read from any replica. With hedge_s set, a backup
        request fires at a second replica if the first hasn't answered
        within the delay and the first response wins — the reference's
        processWithBackupRequest (worker/task.go:66) tail-latency
        defense. `deadline_ms` rides the wire so the serving node
        inherits the remaining budget, AND bounds the client-side
        routed-retry loop to the same clock.

        `best_effort` + `read_ts` is the watermark-bounded follower
        read: ANY replica (learners included) serves it once its
        applied watermark covers read_ts, failing typed (StaleRead)
        instead of blocking past the staleness bound."""
        req = {"op": "query", "q": q, "vars": variables}
        if tenant:
            req["tenant"] = tenant
        if deadline_ms is not None:
            req["deadline_ms"] = int(deadline_ms)
        if best_effort:
            req["be"] = True
        if read_ts is not None:
            req["read_ts"] = read_ts
            if hedge_s is not None and not best_effort:
                # pinned reads are leader-only; the hedge path fires at
                # arbitrary replicas with no leader rerouting
                raise ValueError(
                    "read_ts and hedge_s cannot be combined")
        deadline_s = deadline_ms / 1000.0 \
            if deadline_ms is not None else None
        if hedge_s is not None and len(self.addrs) > 1:
            return self._unwrap(self._hedged(req, hedge_s, deadline_s))
        return self._unwrap(self.request(req, deadline_s=deadline_s))

    def query_at(self, node: int, q: str,
                 variables: Optional[dict] = None,
                 read_ts: Optional[int] = None,
                 deadline_ms: Optional[int] = None,
                 tenant: str = "") -> dict:
        """Best-effort snapshot read at ONE specific replica — the
        read-pool path: RoutedCluster spreads reads across
        voters+learners and retries StaleRead/unreachable elsewhere.
        No leader-following (a follower read is served wherever it
        lands or fails typed); ConnectionError = try another replica."""
        req = {"op": "query", "q": q, "vars": variables, "be": True}
        if tenant:
            req["tenant"] = tenant
        if read_ts is not None:
            req["read_ts"] = int(read_ts)
        if deadline_ms is not None:
            req["deadline_ms"] = int(deadline_ms)
        timeout = deadline_ms / 1000.0 \
            if deadline_ms is not None else None
        resp = self._rpc_once(node, self._traced(req), timeout=timeout)
        if resp is None:
            raise ConnectionError(f"replica {node} unreachable")
        return self._unwrap(resp)

    def _hedged(self, req: dict, hedge_s: float,
                deadline_s: Optional[float] = None) -> dict:
        """Fire at the preferred replica; after hedge_s with no answer,
        race a second replica on a FRESH connection (the pooled conns
        stay owned by the main path). First non-error response wins.
        `deadline_s` bounds the WHOLE hedged wait (else self.timeout)."""
        import queue

        req = self._traced(req)

        budget = self.timeout if deadline_s is None else deadline_s
        overall = time.monotonic() + budget

        with self._lock:
            now = time.monotonic()
            # snapshot the address map: attempt() runs on hedge
            # threads while add_node/remove_node may mutate it
            addrs = dict(self.addrs)
            healthy = [n for n in sorted(addrs)
                       if self._down.get(n, 0) <= now]
            pool = healthy or sorted(addrs)
            first = self._preferred if self._preferred in pool \
                else pool[0]
            down = dict(self._down)
        others = [n for n in sorted(addrs) if n != first]
        others = sorted(others, key=lambda n: down.get(n, 0) > now)
        results: queue.Queue = queue.Queue()

        def attempt(node):
            if netfault.armed() and netfault.act(
                    addrs[node], can_dup=False) == netfault.DROP:
                results.put(None)
                return
            try:
                sock = socket.create_connection(
                    addrs[node], timeout=min(2.0, budget))
                sock.settimeout(budget)
                try:
                    wire.write_frame(sock, wire.dumps(req))
                    results.put(wire.loads(wire.read_frame(sock)))
                finally:
                    sock.close()
            except (OSError, EOFError, wire.WireError):
                results.put(None)

        threads = [threading.Thread(target=attempt, args=(first,),
                                    daemon=True)]
        threads[0].start()
        failures = 0
        try:
            got = results.get(timeout=min(hedge_s,
                                          overall - time.monotonic()))
            if got is not None:
                return got  # ok or a real application error: surface it
            failures += 1   # connection-level failure
        except (queue.Empty, ValueError):
            pass  # ValueError: the budget is already gone
        # primary is slow/dead: hedge to a backup replica — unless the
        # budget is spent, in which case a raced connection + query
        # could never be consumed anyway
        if time.monotonic() < overall:
            threads.append(threading.Thread(target=attempt,
                                            args=(others[0],),
                                            daemon=True))
            threads[1].start()
        while time.monotonic() < overall and failures < len(threads):
            try:
                got = results.get(timeout=max(
                    0.01, overall - time.monotonic()))
            except queue.Empty:
                break
            if got is not None:
                return got
            failures += 1
        # both raced attempts failed to CONNECT: fall back to the
        # routed retry path, within whatever budget remains
        return self.request(req, deadline_s=None if deadline_s is None
                            else max(0.0, overall - time.monotonic()))

    def _call(self, op: str, kw: dict,
              deadline_ms: Optional[int]) -> Any:
        """One deadline-bounded op RPC: `deadline_ms` rides the wire
        (the serving leader inherits the remaining budget, reqctx
        PROPAGATION_SKEW_S wide) AND bounds the routed-retry loop
        here to the same clock — an expired client must not keep a
        leader working on its behalf."""
        req = {"op": op, "kw": kw}
        deadline_s = None
        if deadline_ms is not None:
            req["deadline_ms"] = int(deadline_ms)
            deadline_s = deadline_ms / 1000.0
        return self._unwrap(self.request(req, deadline_s=deadline_s))

    def mutate(self, deadline_ms: Optional[int] = None, **kw) -> dict:
        return self._call("mutate", kw, deadline_ms)

    # dgo-style interactive txns: the group leader stages; commit
    # replicates (a leader change aborts open txns — retry)
    def txn_mutate(self, start_ts: int = 0,
                   deadline_ms: Optional[int] = None, **kw) -> dict:
        kw["commit_now"] = False
        if start_ts:
            kw["start_ts"] = start_ts
        return self._call("mutate", kw, deadline_ms)

    def txn_commit(self, start_ts: int, abort: bool = False) -> dict:
        return self._unwrap(self.request(
            {"op": "commit",
             "params": {"startTs": str(start_ts),
                        "abort": "true" if abort else "false"}}))

    def alter(self, schema_text: str = "",
              deadline_ms: Optional[int] = None, **kw) -> dict:
        kw["schema_text"] = schema_text
        return self._call("alter", kw, deadline_ms)

    def members(self) -> dict:
        return self._unwrap(self.request({"op": "members"}))

    def conf_change(self, action: str, node: int,
                    addr: Optional[tuple[str, int]] = None) -> dict:
        """Add/remove a raft group member (ref conn/raft_server.go
        JoinCluster; zero /removeNode). After an add, call add_node()
        so this client can reach the new member too."""
        req = {"op": "conf_change", "action": action, "node": node}
        if addr is not None:
            req["addr"] = tuple(addr)
        return self._unwrap(self.request(req))

    def add_node(self, node: int, addr: tuple[str, int]):
        with self._lock:
            self.addrs[node] = tuple(addr)

    def remove_node(self, node: int):
        with self._lock:
            self.addrs.pop(node, None)
            sock = self._conns.pop(node, None)
            if self._preferred == node:
                self._preferred = None
        if sock is not None:
            sock.close()

    def subscribe(self, pred: str, offset: int = 0,
                  wait_ms: int = 0, limit: int = 256,
                  sub_id: str = "") -> dict:
        """One CDC poll: entries with offset > `offset` from whichever
        node answers (any replica serves the same stream — offsets are
        deterministic across the group). Raises cdc.OffsetTruncated
        when the resume offset predates the serving node's log floor;
        the caller re-syncs (snapshot read at resync_ts, resubscribe
        from offset_for_ts(resync_ts)).

        Use a DEDICATED ClusterClient per subscriber: a long-poll
        parks the pooled per-node connection for up to wait_ms, and
        the per-node mutex would stall other requests sharing it."""
        resp = self.request(
            {"op": "subscribe", "pred": pred, "offset": int(offset),
             "wait_ms": int(wait_ms), "limit": int(limit),
             "id": sub_id},
            deadline_s=wait_ms / 1000.0 + max(5.0, self.timeout))
        if not resp.get("ok") and resp.get("truncated"):
            from dgraph_tpu.cdc.changelog import OffsetTruncated
            t = resp["truncated"]
            # the wire payload carries the server-derived resync ts
            # explicitly (same camelCase key as the HTTP 410 surface);
            # legacy servers sent only the floor — derive as before
            raise OffsetTruncated(
                t["pred"], int(offset), t["floor"],
                resync_ts=t.get("resyncTs", t.get("resync_ts")))
        return self._unwrap(resp)

    def hello(self, protocol_version: Optional[int] = None) -> dict:
        """Version negotiation (storage/versions.py): returns the
        serving node's {protocol, format, build, negotiated} where
        `negotiated` = min(server's protocol, ours)."""
        from dgraph_tpu.storage.versions import PROTOCOL_VERSION
        pv = PROTOCOL_VERSION if protocol_version is None \
            else int(protocol_version)
        return self._unwrap(self.request(
            {"op": "hello", "protocol_version": pv}))

    def status(self, node: Optional[int] = None) -> dict:
        if node is not None:
            resp = self._rpc_once(node, {"op": "status"})
            if resp is None:
                raise ConnectionError(f"node {node} unreachable")
            return resp["result"]
        return self._unwrap(self.request({"op": "status"}))

    # -------------------------------------------------------- zero surface

    def read_ts(self) -> int:
        """Zero's current max timestamp WITHOUT bumping it — the
        grant for watermark-bounded follower reads (the snapshot at
        this ts is final: every future commit_ts exceeds it)."""
        return self._unwrap(self.request({"op": "read_ts"}))

    def assign_ts(self, n: int = 1) -> int:
        return self._unwrap(self.request(
            {"op": "assign_ts", "args": (n,)}))

    def assign_uids(self, n: int) -> int:
        return self._unwrap(self.request(
            {"op": "assign_uids", "args": (n,)}))

    def commit(self, start_ts: int, keys: list[int]) -> int:
        return self._unwrap(self.request(
            {"op": "commit", "args": (start_ts, list(keys))}))

    def tablet(self, pred: str, group: int) -> int:
        return self._unwrap(self.request(
            {"op": "tablet", "args": (pred, group)}))

    @staticmethod
    def _unwrap(resp: dict) -> Any:
        if not resp.get("ok"):
            # a serving node's typed cancellation/deadline marker
            # (service.py _client_loop) re-raises TYPED here, so the
            # HTTP/gRPC edges map it to 408/499/429 instead of 500
            cls = _ABORT_TYPES.get(resp.get("aborted", ""))
            if cls is not None:
                raise cls(resp.get("error", resp["aborted"]))
            if resp.get("misrouted"):
                # the tablet moved after this client's map was
                # fetched: typed + retryable — RoutedCluster refreshes
                # the map and re-routes instead of surfacing a 500
                from dgraph_tpu.cluster.errors import TabletMisrouted
                m = resp["misrouted"]
                raise TabletMisrouted(m.get("pred", "?"),
                                      m.get("group"),
                                      resp.get("error", ""))
            if resp.get("stale"):
                # a follower read outran this replica's applied
                # watermark: typed + retryable — the router re-issues
                # the read at another replica (the leader always
                # qualifies) instead of surfacing an error
                from dgraph_tpu.cluster.errors import StaleRead
                s = resp["stale"]
                raise StaleRead(int(s.get("readTs", 0)),
                                int(s.get("watermark", -1)),
                                resp.get("error", ""))
            if resp.get("fenced"):
                # the whole cluster refuses client writes (replication
                # standby / fenced old primary) — typed and NOT
                # retryable here: the client must re-point at the
                # active primary
                from dgraph_tpu.cluster.errors import WriteFenced
                raise WriteFenced(resp["fenced"].get("phase", ""),
                                  resp.get("error", ""))
            if resp.get("deadline_expired"):
                # the caller's budget died in the routing loop (e.g.
                # an election outlasted it) — same typed outcome as a
                # server-side expiry
                raise DeadlineExceeded(
                    "deadline exceeded while routing: "
                    + resp.get("error", "rpc failed"))
            raise RuntimeError(resp.get("error", "rpc failed"))
        return resp["result"]
