"""Cross-cluster async replication: a standby cluster tails a primary.

The standby's zero LEADER runs a ReplicationDriver (the same posture
as the tablet-move driver in cluster/service.py): per primary tablet
it streams the base snapshot through the move export surface
(move_export_begin / move_chunk -> move_stage_chunk -> repl_install),
then tails the primary's raw change log (move_deltas — the
cdc/changelog.read_raw contract: whole commits, ascending ts) and
applies batches through the standby group's replicated move_delta
path. The standby tablet's durable max_commit_ts IS the resume point
after any crash on either side — no extra progress records, exactly
the mover's trick. A truncated change log (the bounded raw ring
evicted past our watermark) drops the standby copy and re-snapshots:
the same truncation -> resync contract subscribers get.

Roles are replicated state on the standby's zero (ZeroState.repl_phase
+ write_fence), so a NEW standby zero leader resumes tailing — or
stays promoted — exactly where the old one died. The fence keeps
client writes out of the standby (replication applies bypass the
ownership check by construction); `promote()` fences the PRIMARY,
drains every predicate to the primary's post-fence CDC head (the
write-lock barrier read move_status provides), bumps the standby's
ts/uid leases past the primary's, and flips the standby to a writable
primary — measuring RPO (commits drained after the fence; 0 lost on a
clean promote) and RTO (fence -> writable wall time).

Ref: dgraph's enterprise CDC/backup-based DR story; the in-cluster
analogue is worker/draft.go's move machinery, which this reuses
wholesale across the cluster boundary.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dgraph_tpu.utils import metrics
from dgraph_tpu.utils.logger import log


class PromoteError(RuntimeError):
    """Promotion could not complete safely (primary unreachable
    without force, or the drain did not converge)."""


class ReplicationDriver:
    """Leader-only standby replication loop + promotion, owned by the
    standby cluster's ZeroServer. All cross-cluster I/O goes through
    ClusterClients built per pass (leader-following on both sides)."""

    def __init__(self, zero, primary_addrs: dict[int, tuple[str, int]],
                 tick_s: float = 0.5, batch_limit: int = 512,
                 chunk_bytes: int = 1 << 20,
                 drain_timeout_s: float = 30.0):
        self.zero = zero  # the standby ZeroServer
        self.primary_addrs = dict(primary_addrs)
        self.tick_s = float(tick_s)
        self.batch_limit = int(batch_limit)
        self.chunk_bytes = int(chunk_bytes)
        self.drain_timeout_s = float(drain_timeout_s)
        # leader-local observability (recomputed after a leader
        # change, never authoritative): pred -> {"lag": entries
        # behind the primary head, "applied_ts": standby watermark,
        # "src_head": primary cdc head offset, "caught_up_at":
        # monotonic instant lag last hit 0 (None = never)}
        # guards progress/_primary_ok/_promoting: run() mutates them
        # on the driver thread while lag_payload()/promote() read and
        # flip them from ZeroServer request handlers
        self._lock = threading.Lock()
        self.progress: dict[str, dict] = {}
        self._primary_ok = False
        self._promoting = False

    # ------------------------------------------------------- clients

    def _primary_zero(self):
        from dgraph_tpu.cluster.client import ClusterClient
        return ClusterClient(self.primary_addrs, timeout=30.0)

    @staticmethod
    def _group_client_from(alphas: dict, gid: int):
        from dgraph_tpu.cluster.client import ClusterClient
        addrs = {rec["id"]: tuple(rec["client"])
                 for rec in alphas.values() if rec["group"] == gid}
        return ClusterClient(addrs, timeout=30.0) if addrs else None

    # --------------------------------------------------------- phase

    def phase(self) -> str:
        with self.zero.lock:
            return self.zero.state.repl_phase

    def ensure_standby(self) -> None:
        """Idempotently mark this cluster a fenced standby (replicated
        — survives zero leader changes). Never demotes a promoted
        cluster back: promotion is one-way."""
        with self.zero.lock:
            phase = self.zero.state.repl_phase
            fenced = self.zero.state.write_fence
        if phase in ("promoting", "promoted"):
            return
        if phase != "standby":
            self.zero.propose_and_wait(("repl_phase", ("standby",)))
        if not fenced:
            self.zero.propose_and_wait(("set_write_fence", (True,)))

    # ----------------------------------------------------- main loop

    def run(self) -> None:
        """The standby loop: tick until promoted or shut down."""
        while not self.zero._stop.wait(self.tick_s):
            with self._lock:
                promoting = self._promoting
            if not self.zero.is_leader() or promoting:
                continue
            try:
                if self.tick() == "promoted":
                    return
            except Exception as e:  # noqa: BLE001 — retry next tick  # dglint: disable=DG07 (standby replication daemon; no request context)
                log.warning("repl_tick_retry", error=str(e)[:200])

    def tick(self) -> str:
        """One replication pass over every primary tablet. Returns the
        phase so the loop can stop once promoted."""
        self.ensure_standby()
        phase = self.phase()
        if phase == "promoted":
            return phase
        pz = self._primary_zero()
        try:
            got = pz.request({"op": "cluster_state"})
            if not got.get("ok"):
                with self._lock:
                    self._primary_ok = False
                return phase
            cstate = got["result"]
            st = pz.request({"op": "status"})
            with self._lock:
                self._primary_ok = True
            if st.get("ok"):
                # keep the standby's ts/uid leases at or past the
                # primary's: post-promotion timestamps must never
                # collide with replicated commits
                self.zero.propose_and_wait(
                    ("bump_maxes", (int(st["result"]["max_ts"]),
                                    int(st["result"]["next_uid"]))))
            for pred, src_gid in sorted(cstate["tablets"].items()):
                try:
                    self._sync_pred(pred, int(src_gid),
                                    cstate["alphas"])
                except Exception as e:  # noqa: BLE001 — per-pred isolation  # dglint: disable=DG07 (standby replication daemon; no request context)
                    log.warning("repl_sync_retry", pred=pred,
                                error=str(e)[:200])
            # hash-range split predicates have no single source group;
            # replicating them would need per-shard tails — out of
            # scope, surfaced rather than silently skipped
            for pred in cstate.get("splits", {}):
                with self._lock:
                    self.progress.setdefault(pred, {})[
                        "unsupported"] = ("split predicate "
                                          "(replicate before "
                                          "splitting)")
        finally:
            pz.close()
        return self.phase()

    # ------------------------------------------------------ per-pred

    def _dst_group(self, pred: str) -> Optional[int]:
        """The standby group serving this predicate — claimed on
        first sight at the standby's LEAST-POPULATED group (the
        standby's zero owns its own placement; primary group ids
        need not exist over here)."""
        with self.zero.lock:
            owned = self.zero.state.tablets.get(pred)
            groups = sorted({rec["group"] for rec
                             in self.zero.state.alphas.values()})
            counts = {g: 0 for g in groups}
            for p, g in self.zero.state.tablets.items():
                if g in counts:
                    counts[g] += 1
        if owned is not None:
            return owned
        if not groups:
            return None  # no standby alphas registered yet
        gid = min(groups, key=lambda g: (counts[g], g))
        ok, got = self.zero.propose_and_wait(("tablet", (pred, gid)))
        return int(got) if ok else None

    def _sync_pred(self, pred: str, src_gid: int,
                   primary_alphas: dict) -> None:
        dst_gid = self._dst_group(pred)
        if dst_gid is None:
            return
        src_cl = self._group_client_from(primary_alphas, src_gid)
        dst_cl = self.zero._group_client(dst_gid)
        if src_cl is None or dst_cl is None:
            for cl in (src_cl, dst_cl):
                if cl is not None:
                    cl.close()
            return
        try:
            st = dst_cl._unwrap(dst_cl.request(
                {"op": "move_dst_status", "pred": pred}))
            if not st["installed"]:
                self._snapshot_pred(pred, src_cl, dst_cl, st)
                st = dst_cl._unwrap(dst_cl.request(
                    {"op": "move_dst_status", "pred": pred}))
            self._tail_pred(pred, src_cl, dst_cl,
                            int(st["max_commit_ts"]))
        finally:
            src_cl.close()
            dst_cl.close()

    def _snapshot_pred(self, pred: str, src_cl, dst_cl,
                       st: dict) -> None:
        """Stream the base copy primary -> standby through the move
        export surface (chunks are re-deliverable; an interrupted
        stream resumes from the staged sequence)."""
        begin = src_cl._unwrap(src_cl.request(
            {"op": "move_export_begin", "pred": pred,
             "prefer_snap_ts": st.get("staged_snap_ts", 0),
             "chunk_bytes": self.chunk_bytes}))
        snap_ts = int(begin["snap_ts"])
        nchunks = int(begin["chunks"])
        first = 0
        if snap_ts and snap_ts == int(st.get("staged_snap_ts", 0)):
            first = min(int(st.get("have_chunks", 0)), nchunks)
        for seq in range(first, nchunks):
            if self.zero._stop.is_set() or not self.zero.is_leader():
                return
            got = src_cl._unwrap(src_cl.request(
                {"op": "move_chunk", "pred": pred,
                 "snap_ts": snap_ts, "seq": seq}))
            dst_cl._unwrap(dst_cl.request(
                {"op": "move_stage_chunk", "pred": pred,
                 "snap_ts": snap_ts, "seq": seq, "total": nchunks,
                 "data": got["data"]}))
            metrics.inc_counter("dgraph_repl_streamed_bytes_total",
                                len(got["data"]))
        inst = dst_cl.request({"op": "repl_install", "pred": pred,
                               "snap_ts": snap_ts})
        if not inst.get("ok"):
            raise RuntimeError(f"repl install {pred!r}: "
                               f"{inst.get('error')}")
        src_cl.request({"op": "move_export_end", "pred": pred})

    def _tail_pred(self, pred: str, src_cl, dst_cl,
                   have_ts: int, rounds: int = 64) -> int:
        """Tail the primary's raw change log onto the standby until
        caught up (or `rounds` batches). Returns the remaining lag in
        change-log entries; records per-pred progress."""
        from dgraph_tpu.cdc.changelog import offset_for_ts
        with self._lock:
            prog = self.progress.setdefault(
                pred, {"lag": None, "applied_ts": 0, "src_head": 0,
                       "caught_up_at": None, "commits_applied": 0})
            prog.pop("unsupported", None)
        for _ in range(rounds):
            if self.zero._stop.is_set():
                return prog["lag"] or 0
            got = src_cl.request(
                {"op": "move_deltas", "pred": pred,
                 "after": offset_for_ts(have_ts),
                 "limit": self.batch_limit})
            if not got.get("ok"):
                if got.get("truncated"):
                    # the primary's bounded ring evicted past our
                    # watermark: drop the stale standby copy and
                    # re-snapshot (truncation -> resync contract)
                    dst_cl.request({"op": "drop_tablet", "pred": pred})
                    log.info("repl_resync", pred=pred,
                             resync_ts=got["truncated"].get("resyncTs"))
                    return prog["lag"] or 0
                raise RuntimeError(f"deltas {pred!r}: "
                                   f"{got.get('error')}")
            res = got["result"]
            if res["batches"]:
                ap = dst_cl.request({"op": "move_apply", "pred": pred,
                                     "batches": res["batches"]})
                if not ap.get("ok"):
                    raise RuntimeError(f"apply {pred!r}: "
                                       f"{ap.get('error')}")
                have_ts = int(ap["result"]["max_commit_ts"])
                prog["commits_applied"] = \
                    prog.get("commits_applied", 0) + len(res["batches"])
            lag = int(res["behind"])
            prog["lag"] = lag
            prog["applied_ts"] = have_ts
            prog["src_head"] = int(res.get("head", 0))
            metrics.set_gauge("dgraph_repl_lag_entries", lag,
                              labels={"pred": pred})
            if not res["batches"] and not lag:
                prog["caught_up_at"] = time.monotonic()
                return 0
        return prog["lag"] or 0

    # ----------------------------------------------------- promotion

    def lag_payload(self) -> dict:
        """Per-predicate replication lag for /debug/stats and dgtop:
        entries behind the primary head, plus seconds since this
        predicate was last fully caught up (the freshness signal the
        runbook's RPO estimate reads)."""
        now = time.monotonic()
        preds = {}
        with self._lock:
            snapshot = sorted(self.progress.items())
            primary_ok = self._primary_ok
        for pred, prog in snapshot:
            if "unsupported" in prog:
                preds[pred] = {"unsupported": prog["unsupported"]}
                continue
            at = prog.get("caught_up_at")
            preds[pred] = {
                "lag": prog.get("lag"),
                "applied_ts": prog.get("applied_ts", 0),
                "lag_s": round(now - at, 3) if at is not None
                else None}
        return {"phase": self.phase(),
                "primary_reachable": primary_ok,
                "preds": preds}

    def promote(self, force: bool = False) -> dict:
        """Promote this standby to a writable primary. Clean path:
        fence the primary's client writes, drain every predicate to
        the primary's POST-FENCE cdc head (move_status acquires the
        primary group's write lock, so that head covers every commit
        that passed its ownership check), then flip. RPO = commits
        drained after the fence landed (0 lost); RTO = fence ->
        writable wall time. With the primary unreachable, `force`
        promotes on the standby's last applied state — RPO is then the
        unreplicated tail, surfaced as rpo_clean=False."""
        with self._lock:
            if self._promoting:
                raise PromoteError("promotion already in progress")
            self._promoting = True
        t0 = time.monotonic()
        try:
            clean = True
            heads: dict[str, int] = {}
            pz = self._primary_zero()
            try:
                fenced = pz.request({"op": "set_write_fence",
                                     "args": (True,)})
                if not fenced.get("ok"):
                    raise RuntimeError(fenced.get("error",
                                                  "fence refused"))
                cstate = pz._unwrap(pz.request({"op": "cluster_state"}))
                st = pz._unwrap(pz.request({"op": "status"}))
                self.zero.propose_and_wait(
                    ("bump_maxes", (int(st["max_ts"]),
                                    int(st["next_uid"]))))
            except Exception as e:  # noqa: BLE001 — unreachable primary is the promotion's input condition  # dglint: disable=DG07 (disaster-recovery path; error becomes the rpo_clean flag)
                if not force:
                    raise PromoteError(
                        "primary unreachable; promote with force=True "
                        "to accept losing the unreplicated tail "
                        f"({e})") from e
                clean = False
                cstate = None
            finally:
                pz.close()
            self.zero.propose_and_wait(("repl_phase", ("promoting",)))
            drained = 0
            if clean and cstate is not None:
                drained, heads = self._drain(cstate)
            self.zero.propose_and_wait(("repl_phase", ("promoted",)))
            self.zero.propose_and_wait(("set_write_fence", (False,)))
            rto_ms = round((time.monotonic() - t0) * 1000, 1)
            out = {"promoted": True, "rpo_clean": clean,
                   "rpo_commits_drained": drained,
                   "rto_ms": rto_ms,
                   "preds": {p: {"drained_to_head": h}
                             for p, h in sorted(heads.items())}}
            if not clean:
                out["rpo_note"] = ("primary unreachable: commits past "
                                   "each predicate's applied_ts are "
                                   "lost")
                with self._lock:
                    out["preds"] = {
                        p: {"applied_ts": prog.get("applied_ts", 0),
                            "last_known_lag": prog.get("lag")}
                        for p, prog in sorted(self.progress.items())}
            metrics.observe("dgraph_repl_promote_rto_ms", rto_ms)
            log.info("standby_promoted", clean=clean,
                     drained=drained, rto_ms=rto_ms)
            return out
        finally:
            with self._lock:
                self._promoting = False

    def _drain(self, cstate: dict) -> tuple[int, dict]:
        """Drain every predicate to the fenced primary's cdc head.
        Returns (commits applied during the drain, per-pred head)."""
        from dgraph_tpu.cdc.changelog import offset_for_ts
        deadline = time.monotonic() + self.drain_timeout_s
        drained = 0
        heads: dict[str, int] = {}
        for pred, src_gid in sorted(cstate["tablets"].items()):
            dst_gid = self._dst_group(pred)
            if dst_gid is None:
                raise PromoteError(
                    f"no standby group for {pred!r}; cannot drain")
            src_cl = self._group_client_from(cstate["alphas"],
                                             int(src_gid))
            dst_cl = self.zero._group_client(dst_gid)
            if src_cl is None or dst_cl is None:
                raise PromoteError(f"groups unreachable for {pred!r}")
            with self._lock:
                c0 = self.progress.get(pred, {}) \
                    .get("commits_applied", 0)
            try:
                # the barrier read: after the fence, move_status's
                # write-lock acquisition proves every in-flight commit
                # has fully applied and is covered by this head
                sst = src_cl._unwrap(src_cl.request(
                    {"op": "move_status", "pred": pred}))
                head = int(sst["cdc_head"])
                heads[pred] = head
                while True:
                    st = dst_cl._unwrap(dst_cl.request(
                        {"op": "move_dst_status", "pred": pred}))
                    if not st["installed"]:
                        self._snapshot_pred(pred, src_cl, dst_cl, st)
                        continue
                    covered = offset_for_ts(int(st["max_commit_ts"]))
                    if covered >= head:
                        break
                    self._tail_pred(pred, src_cl, dst_cl,
                                    int(st["max_commit_ts"]), rounds=8)
                    if time.monotonic() > deadline:
                        raise PromoteError(
                            f"drain of {pred!r} did not converge "
                            f"within {self.drain_timeout_s}s "
                            f"(covered {covered} < head {head})")
                with self._lock:
                    drained += self.progress.get(pred, {}) \
                        .get("commits_applied", 0) - c0
            finally:
                src_cl.close()
                dst_cl.close()
        return drained, heads
