"""Replicated engine group: GraphDB state machine over Raft.

The reference model (worker/draft.go): every Alpha group is a Raft
group; mutations are proposed to the group leader, replicated, then
applied by each member's apply loop. Here the proposal payload is
exactly the engine's durable WAL record (GraphDB.apply_record is shared
between WAL replay and the Raft apply path), so a follower's state
matches the leader's record-for-record.

Write path (ref worker/mutation.go:537 MutateOverNetwork →
proposal.go:113 proposeAndWait): the mutation executes on the leader
replica's engine — allocating uids/ts and producing the expanded commit
record via the engine's on_record sink — then the record is proposed to
the group. Followers apply it; the leader skips re-applying its own
records (its engine already holds the txn result). Origins carry a
per-process epoch so a restarted replica re-applies records it proposed
in a previous life (its rebuilt engine doesn't have them).

Reads go to any replica (followers serve snapshot reads like the
reference's best-effort queries, edgraph/server.go:760).

Snapshots: checkpoint() folds the engine state into a Raft snapshot
(storage.snapshot.dump_state) and compacts the log; a lagging or fresh
member is restored from it via InstallSnapshot (ref worker/snapshot.go
doStreamSnapshot/populateSnapshot).

The driver here is synchronous-deterministic (SimCluster); a network
transport swaps in at the Msg layer without touching this file.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

from dgraph_tpu import wire

from dgraph_tpu.cluster.harness import SimCluster
from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.storage.snapshot import dump_state, restore_state


class ReplicatedGroup:
    """N-replica engine group over a simulated Raft transport."""

    def __init__(self, n: int = 3, seed: int = 0,
                 storage_factory=None, **db_kw):
        self.cluster = SimCluster(n, seed=seed,
                                  storage_factory=storage_factory)
        db_kw.setdefault("prefer_device", False)
        self._db_kw = db_kw
        self.dbs: dict[int, GraphDB] = {
            i: GraphDB(**db_kw) for i in self.cluster.ids}
        self._epoch: dict[int, int] = {i: 0 for i in self.cluster.ids}
        self._acked: dict[int, set] = {i: set() for i in self.cluster.ids}
        # committed event stream per node (snapshot resets + records):
        # the authoritative source to rebuild an engine whose local
        # pre-consensus apply turned out not to replicate
        self._events: dict[int, list] = {i: [] for i in self.cluster.ids}
        self._mark_seq = itertools.count(1)
        self.cluster.on_apply = self._apply
        self.cluster.on_restore = self._restore
        self.cluster.wait_leader()

    # ------------------------------------------------------------- apply

    def _apply(self, node_id: int, data: Any):
        mark, origin, rec = data
        self._acked[node_id].add(mark)
        self._events[node_id].append(("rec", rec))
        if origin == (node_id, self._epoch[node_id]):
            # the proposing replica already holds this state (its local
            # engine executed the txn); don't double-apply
            return
        db = self.dbs[node_id]
        ts = db.apply_record(rec)
        if ts:
            db.fast_forward_ts(ts)

    def _restore(self, node_id: int, snap: bytes):
        """InstallSnapshot: rebuild the replica's engine from the
        serialized state (ref worker/snapshot.go populateSnapshot)."""
        self._events[node_id] = [("snap", snap)]
        self.dbs[node_id] = restore_state(wire.loads_compat(snap),
                                          GraphDB(**self._db_kw))

    def _rebuild(self, node_id: int):
        """Discard a replica's un-replicated local state: rebuild its
        engine purely from the committed event stream. Used when a
        leader pre-applied a txn (for ts/uid allocation) whose record
        then failed to reach quorum — the Raft analogue of a deposed
        leader dropping its uncommitted tail."""
        self._epoch[node_id] += 1  # past own-origin records must re-apply
        db = GraphDB(**self._db_kw)
        for kind, payload in self._events[node_id]:
            if kind == "snap":
                db = restore_state(wire.loads_compat(payload), db)
            else:
                ts = db.apply_record(payload)
                if ts:
                    db.fast_forward_ts(ts)
        self.dbs[node_id] = db

    # ------------------------------------------------------------- writes

    def _propose_record(self, origin_id: int, rec) -> bool:
        mark = next(self._mark_seq)
        origin = (origin_id, self._epoch[origin_id])
        if not self.cluster.propose((mark, origin, rec)):
            return False
        for _ in range(200):  # wait until the origin's replica applied it
            if mark in self._acked[origin_id]:
                return True
            self.cluster.pump()
        return False

    def leader_id(self) -> int:
        lead = self.cluster.leader()
        if lead is None:
            lead = self.cluster.wait_leader()
        return lead

    def alter(self, schema_text: str = "", **kw):
        lead = self.leader_id()
        recs = self._run_with_sink(lead, lambda db: db.alter(
            schema_text, **kw))
        self._replicate(lead, recs, "alter")

    def mutate(self, **kw) -> dict:
        """Execute on the leader engine, replicate its commit record."""
        lead = self.leader_id()
        out: dict = {}

        def run(db):
            out.update(db.mutate(commit_now=True, **kw))

        recs = self._run_with_sink(lead, run)
        self._replicate(lead, recs, "mutation")
        return out

    def _replicate(self, lead: int, recs: list, what: str):
        for rec in recs:
            if not self._propose_record(lead, rec):
                # quorum unreachable: roll the pre-applied state back so
                # this replica never serves phantom data
                self._rebuild(lead)
                raise RuntimeError(f"{what} not replicated (no quorum)")

    def _run_with_sink(self, node_id: int, fn) -> list:
        db = self.dbs[node_id]
        captured: list = []
        prev = db.on_record
        db.on_record = captured.append
        try:
            fn(db)
        finally:
            db.on_record = prev
        return captured

    # ------------------------------------------------------------- reads

    def query(self, q: str, node: Optional[int] = None, **kw) -> dict:
        node = node if node is not None else self.leader_id()
        return self.dbs[node].query(q, **kw)

    # --------------------------------------------------------- snapshots

    def checkpoint(self, node: Optional[int] = None):
        """Compact the Raft log into an engine snapshot on `node`
        (default: leader). Ref worker/draft.go:1206 calculateSnapshot."""
        node = node if node is not None else self.leader_id()
        snap = wire.dumps(dump_state(self.dbs[node]))
        self.cluster.nodes[node].take_snapshot(snap)

    # ---------------------------------------------------------- failures

    def kill(self, node_id: int):
        self.cluster.kill(node_id)

    def restart(self, node_id: int):
        """Replica restarts with a fresh engine; its state is rebuilt
        from the Raft log (and/or snapshot) alone."""
        self.dbs[node_id] = GraphDB(**self._db_kw)
        self._epoch[node_id] += 1
        self._acked[node_id] = set()
        self._events[node_id] = []  # re-deliveries repopulate it
        self.cluster.restart(node_id)
        self.cluster.pump(5)

    def pump(self, n: int = 1):
        self.cluster.pump(n)
