"""Multi-group topology: predicate-sharded groups behind one client.

The reference shards data by PREDICATE across Alpha groups: Zero owns
the tablet->group map (zero/tablet.go), alphas serve only their
tablets, queries/mutations route per predicate (worker/groups.go
BelongsTo, worker/task.go:131 attr routing), and the rebalancer moves
tablets between groups (zero/tablet.go:62 movetablet,
worker/predicate_move.go). RoutedCluster is that tier's client side:
it consults the replicated Zero quorum for ownership, claims unowned
predicates on first write (least-loaded group), refuses writes to
tablets mid-move, and orchestrates live tablet moves
(export -> import -> flip -> drop).

Cross-group contract (all three tiers, fastest first):
  1. every predicate on one group -> route the whole request there;
  2. top-level blocks on different groups -> scatter block-wise at one
     global read_ts and gather;
  3. a single block spanning groups, or variables crossing groups ->
     FEDERATED execution (cluster/federated.py): the unchanged query
     executor runs here with per-attr task RPCs to each owning group
     (ref worker/task.go:131 ProcessTaskOverNetwork).
Mutations spanning groups run as one atomic transaction: per-group
replicated stages + a single commit decision recorded in the Zero
oracle (2PC; ref worker/mutation.go:472, zero/oracle.go:326).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from dgraph_tpu.cluster.client import ClusterClient
from dgraph_tpu.cluster.errors import StaleRead, TabletMisrouted


class SpanGroupsError(RuntimeError):
    """A request's predicates resolve to more than one group — the
    signal (internal to this module) that the cross-group path must
    run: block-wise scatter, federated execution, or a 2PC mutation."""

    def __init__(self, preds, owners):
        super().__init__(
            f"predicates {sorted(preds)} span groups {sorted(owners)}")
        self.preds = preds
        self.owners = owners


class _NeedsFederation(Exception):
    """Block-wise scatter can't serve this query (a single block spans
    groups, or a variable crosses groups): run it federated."""


class RoutedCluster:
    def __init__(self, zero: ClusterClient,
                 groups: dict[int, ClusterClient]):
        self.zero = zero
        self.groups = dict(groups)
        # read scale-out state: per-group read pools spanning voters
        # AND learners, refreshed from zero's membership so a learner
        # joining mid-run starts taking reads without a client restart
        # (the reference's StreamMembership push, realized as a
        # bounded-staleness pull); `groups` itself stays voters-only —
        # writes and pinned reads never land on a learner
        self._read_pools: dict[int, dict] = {}
        self._read_lock = threading.Lock()
        self._rr = 0
        self._read_ts_grant: tuple[int, float] = (0, -1.0)

    # ------------------------------------------------------------- routing

    def tablet_map(self) -> dict:
        resp = self.zero.request({"op": "tablet_map"})
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "zero unreachable"))
        return resp["result"]

    def _preds_of_query(self, q: str, variables=None) -> set[str]:
        from dgraph_tpu.gql import parse
        from dgraph_tpu.server.acl import query_predicates
        return {p.lstrip("~") for p in
                query_predicates(parse(q, variables))}

    def _preds_of_mutation(self, kw: dict) -> set[str]:
        from dgraph_tpu.server.acl import (
            nquad_predicates, query_predicates,
        )
        preds = set(nquad_predicates(
            kw.get("set_nquads", ""), kw.get("del_nquads", ""),
            kw.get("set_json"), kw.get("delete_json")))
        if kw.get("query"):
            from dgraph_tpu.gql import parse
            preds |= set(query_predicates(
                parse(kw["query"], kw.get("variables"))))
        return {p.lstrip("~") for p in preds if p != "*"}

    def _group_for(self, preds: set[str], claim: bool,
                   tmap: Optional[dict] = None,
                   for_write: bool = False) -> int:
        """Resolve the single group serving `preds`; with claim=True,
        unowned predicates are claimed for the chosen group (ref
        zero.go ShouldServe: first writer claims the tablet).

        Only WRITES respect the moving fence (the move machine's
        short `fenced` phase) — reads never fence: the source keeps
        serving snapshot-consistent reads through every move phase
        until the flip, and post-flip routing points at the
        destination. A hash-range split predicate always has multiple
        owners, so it routes through the cross-group paths."""
        if tmap is None:
            tmap = self.tablet_map()
        if for_write:
            moving = tmap["moving"]
            for p in preds:
                if p in moving:
                    raise RuntimeError(
                        f"tablet {p!r} is being moved; retry shortly")
        splits = tmap.get("splits", {})
        owners = {tmap["tablets"][p] for p in preds
                  if p in tmap["tablets"]}
        for p in preds:
            if p in splits:
                owners.update(int(g) for g in splits[p]["owners"])
        if len(owners) > 1:
            raise SpanGroupsError(preds, owners)
        unowned = [p for p in preds if p not in tmap["tablets"]
                   and p not in splits]
        if owners:
            gid = owners.pop()
        elif not unowned:
            gid = min(self.groups)  # no predicates at all (uid-only)
        else:
            # least-loaded group by tablet count (the rebalancer's
            # heuristic inverted: place new tablets where it's empty)
            counts = {g: 0 for g in self.groups}
            for owner in tmap["tablets"].values():
                if owner in counts:
                    counts[owner] += 1
            gid = min(sorted(counts), key=lambda g: counts[g])
        if claim:
            for p in unowned:
                got = self.zero.tablet(p, gid)
                if got != gid:
                    raise RuntimeError(
                        f"tablet {p!r} was claimed by group {got} "
                        "concurrently; retry")
        return gid

    # ------------------------------------------------------------- surface

    def alter(self, schema_text: str = "", **kw):
        """Schema is cluster-wide: broadcast to every group (the
        reference stores schema per group for its tablets; replicating
        the full text everywhere is a superset with identical
        semantics)."""
        for gid in sorted(self.groups):
            self.groups[gid].alter(schema_text, **kw)

    # bounded re-route budget for requests racing a tablet move: a
    # typed TabletMisrouted (the owner flipped after our map fetch)
    # re-fetches the map and re-routes immediately; a write-fence
    # rejection ("is being moved") backs off and retries — the fence
    # is bounded by zero's --move-fence-timeout, so the whole budget
    # comfortably outlasts one fence window. Neither ever surfaces to
    # the user inside the budget.
    MISROUTE_RETRIES = 4
    FENCE_RETRY_S = 8.0

    def _retry_routed(self, fn):
        """Run `fn()` (which fetches a FRESH tablet map each attempt)
        under the misroute/fence retry contract above."""
        deadline = time.monotonic() + self.FENCE_RETRY_S
        misroutes = 0
        delay = 0.05
        while True:
            try:
                return fn()
            except TabletMisrouted:
                misroutes += 1
                if misroutes > self.MISROUTE_RETRIES:
                    raise
                continue  # next attempt re-fetches the map: re-route
            except RuntimeError as e:
                if "is being moved" not in str(e) \
                        or time.monotonic() >= deadline:
                    raise
                time.sleep(delay)  # fenced: short bounded backoff
                delay = min(0.4, delay * 2)

    def mutate(self, **kw) -> dict:
        def attempt():
            try:
                gid = self._group_for(self._preds_of_mutation(kw),
                                      claim=True, for_write=True)
            except SpanGroupsError:
                return self._mutate_multigroup(kw)
            return self.groups[gid].mutate(**kw)
        return self._retry_routed(attempt)

    def _mutate_multigroup(self, kw: dict) -> dict:
        """One mutation split across groups, committed atomically
        through Zero's oracle (2PC with Zero as transaction manager —
        ref worker/mutation.go:472 populateMutationMap fanning
        per-group fragments, zero/oracle.go:326 the single commit
        decision):

          1. blanks resolve to zero-leased uids BEFORE the split, so
             every group names the same entities
          2. each owning group replicates an xstage fragment at one
             global start_ts and reports its conflict keys
          3. zero's oracle decides (commit_ts or conflict abort) and
             RECORDS the decision — a participant that misses the
             finalize recovers it from zero (txn_status)
          4. xfinalize applies each fragment at commit_ts
        """
        from dgraph_tpu.gql.nquad import (
            nquad_to_wire, parse_json_mutation, parse_rdf,
        )

        if kw.get("query") or kw.get("mutations") or kw.get("cond"):
            raise RuntimeError(
                "a cross-group upsert/conditional mutation is not "
                "supported; split it, or move the tablets together")
        # caller-pinned start_ts: the read-modify-write flow reads its
        # snapshot AT the txn's start_ts (pinned queries), so any
        # commit that lands between read and commit conflicts properly
        pinned_start = int(kw.get("start_ts", 0) or 0)
        nqs = []
        if kw.get("set_nquads"):
            nqs += [(n, False) for n in parse_rdf(kw["set_nquads"])]
        if kw.get("set_json") is not None:
            nqs += [(n, False)
                    for n in parse_json_mutation(kw["set_json"])]
        if kw.get("del_nquads"):
            nqs += [(n, True) for n in parse_rdf(kw["del_nquads"])]
        if kw.get("delete_json") is not None:
            nqs += [(n, True) for n in parse_json_mutation(
                kw["delete_json"], delete=True)]
        if any(nq.predicate == "*" for nq, _ in nqs):
            raise RuntimeError(
                "S * * wildcard deletes cannot span groups; delete "
                "per predicate or move the tablets together")

        # blanks -> one zero lease, substituted before the split
        blanks: dict[str, int] = {}
        for nq, _ in nqs:
            for ref in (nq.subject, nq.object_id):
                if ref and ref.startswith("_:"):
                    blanks.setdefault(ref, 0)
        if blanks:
            first = self.zero.assign_uids(len(blanks))
            for i, k in enumerate(sorted(blanks)):
                blanks[k] = first + i

        tmap = self.tablet_map()
        splits = tmap.get("splits", {})
        by_group: dict[int, list] = {}
        for nq, is_del in nqs:
            if nq.subject in blanks or nq.object_id in blanks:
                from dataclasses import replace as _rp
                nq = _rp(nq,
                         subject=hex(blanks[nq.subject])
                         if nq.subject in blanks else nq.subject,
                         object_id=hex(blanks[nq.object_id])
                         if nq.object_id in blanks else nq.object_id)
            if nq.predicate in splits:
                # hash-range split: route per resolved SUBJECT uid
                # (blanks were substituted above, so every row has
                # one) — the 2PC stage below makes the cross-shard
                # write atomic exactly like any cross-group write
                from dgraph_tpu.cluster.shard import owner_for_uid
                try:
                    uid = int(nq.subject, 0)
                except ValueError:
                    raise RuntimeError(
                        f"cannot route a write to split tablet "
                        f"{nq.predicate!r}: subject {nq.subject!r} "
                        "is not a resolved uid") from None
                gid = owner_for_uid(splits[nq.predicate], uid)
            else:
                gid = tmap["tablets"].get(nq.predicate)
                if gid is None:
                    gid = self._group_for({nq.predicate}, claim=True,
                                          tmap=tmap)
                    tmap["tablets"][nq.predicate] = gid
            by_group.setdefault(gid, []).append(
                (nquad_to_wire(nq), is_del))

        start_ts = pinned_start or self.zero.assign_ts(1)
        keys: set[int] = set()
        staged: list[int] = []
        try:
            for gid in sorted(by_group):
                res = self.groups[gid]._unwrap(self.groups[gid].request(
                    {"op": "xstage", "start_ts": start_ts,
                     "nqs": by_group[gid]}))
                staged.append(gid)
                keys.update(res["keys"])
        except Exception:
            # stage failed somewhere: record the abort at zero FIRST
            # (so nothing can commit this ts later), then best-effort
            # clear the fragments that did stage
            try:
                self.zero.request({"op": "abort_txn",
                                   "args": (start_ts,)})
            except Exception:  # noqa: BLE001  # dglint: disable=DG07 (best-effort abort record inside a handler that re-raises)
                pass
            self._xabort(staged, start_ts)
            raise
        commit_ts = self.zero.commit(start_ts, sorted(keys))
        if not commit_ts:
            self._xabort(staged, start_ts)
            raise RuntimeError(
                f"transaction aborted: write-write conflict at "
                f"startTs {start_ts}")
        for gid in staged:
            try:
                self.groups[gid].request(
                    {"op": "xfinalize", "start_ts": start_ts,
                     "commit_ts": commit_ts})
            except Exception:  # noqa: BLE001 — the decision is  # dglint: disable=DG07 (finalize delivery is best-effort BY CONTRACT; reconcile covers it)
                pass  # recorded; the group reconciles from zero
        return {"uids": {k[2:]: hex(v) for k, v in blanks.items()},
                "extensions": {"txn": {"start_ts": start_ts,
                                       "commit_ts": commit_ts,
                                       "groups": staged}}}

    def _xabort(self, gids, start_ts: int):
        for gid in gids:
            try:
                self.groups[gid].request(
                    {"op": "xfinalize", "start_ts": start_ts,
                     "commit_ts": 0})
            except Exception:  # noqa: BLE001 — reconciliation covers it  # dglint: disable=DG07 (abort fan-out is best-effort BY CONTRACT)
                pass

    def query(self, q: str, variables: Optional[dict] = None,
              deadline_ms: Optional[int] = None,
              best_effort: bool = False, tenant: str = "") -> dict:
        """Route to the owning group; when a document's top-level
        blocks touch DIFFERENT groups, scatter block-wise and gather
        (the reference fans per-attr tasks to group leaders,
        worker/task.go:131; block-level is the coarser granularity the
        predicate-sharded store supports without cross-group joins —
        blocks connected by variables must stay within one group).
        `deadline_ms` bounds the whole routed query: the remaining
        budget rides every downstream RPC (groups/tasks inherit it).

        `best_effort` reads spread across the group's READ POOL
        (voters + learners) as watermark-bounded follower reads at a
        shared zero-granted read_ts; cross-group documents fall back
        to the leader-routed paths unchanged."""
        from dgraph_tpu.gql import parse
        from dgraph_tpu.server.acl import query_predicates

        ctx = None
        if deadline_ms is not None:
            from dgraph_tpu.utils.reqctx import RequestContext
            ctx = RequestContext.from_deadline_ms(deadline_ms)
        parsed = parse(q, variables)
        preds = {p.lstrip("~") for p in query_predicates(parsed)}

        def attempt():
            tmap = self.tablet_map()
            try:
                gid = self._group_for(preds, claim=False, tmap=tmap)
            except SpanGroupsError:
                # one map drives both the span decision and the
                # per-block assignment — no second fetch, no TOCTOU
                # between them
                try:
                    return self._scatter_query(q, variables, parsed,
                                               tmap, ctx)
                except _NeedsFederation:
                    # a single block spans groups / a var crosses
                    # groups / a split sub-tablet fan-out: run the
                    # full executor here with per-attr task RPCs to
                    # each owning group (ref worker/task.go:131)
                    return self._federated_query(q, variables,
                                                 tmap, ctx)
            if best_effort:
                return self._be_query(gid, q, variables, ctx, tenant)
            return self.groups[gid].query(
                q, variables,
                deadline_ms=ctx.remaining_ms() if ctx else None)
        # a move's flip between our map fetch and the read lands a
        # TYPED TabletMisrouted (never silent empties): re-fetch the
        # map and re-route, bounded — queries never fence, so "is
        # being moved" cannot surface here
        return self._retry_routed(attempt)

    # ------------------------------------------------- follower reads

    # membership refresh cadence for the per-group read pools: a new
    # learner starts taking reads within this bound; a dead one costs
    # at most one failed dial per pass until the next refresh
    READ_POOL_REFRESH_S = 2.0
    # best-effort reads within one window share a single zero-granted
    # read_ts (the "read_ts-class"): zero grants one ts per window
    # instead of one per read — the grant RPC drops off the read hot
    # path — and every replica's result cache keys the window's reads
    # identically, so concurrent hot queries hit across requests
    READ_TS_WINDOW_S = 0.05

    def _granted_read_ts(self) -> int:
        """The current read window's timestamp (cached ~50 ms)."""
        now = time.monotonic()
        with self._read_lock:
            ts, at = self._read_ts_grant
            if ts and now - at < self.READ_TS_WINDOW_S:
                return ts
        # non-bumping grant: zero's CURRENT max ts. A fresh assign_ts
        # would stall idle clusters — no commit ever lands on a
        # read-only allocation, so no replica's applied watermark
        # could ever cover it
        fresh = self.zero.read_ts()
        with self._read_lock:
            # two racers both fetch: keep the NEWER grant (read_ts
            # never goes backwards within a client)
            if fresh > self._read_ts_grant[0]:
                self._read_ts_grant = (fresh, now)
            return self._read_ts_grant[0]

    def _read_pool(self, gid: int) -> tuple[ClusterClient, list[int]]:
        """The read-serving client for `gid`: every replica (voters +
        learners) from zero's membership, falling back to the write
        client's voter addrs when zero has no record (e.g. a
        statically-configured group that never registered)."""
        now = time.monotonic()
        with self._read_lock:
            st = self._read_pools.get(gid)
            if st is not None \
                    and now - st["at"] < self.READ_POOL_REFRESH_S:
                return st["client"], st["order"]
        addrs: dict[int, tuple] = {}
        resp = self.zero.request({"op": "cluster_state"})
        if resp.get("ok"):
            for rec in resp["result"].get("alphas", {}).values():
                if int(rec.get("group", 0)) == int(gid):
                    addrs[int(rec["id"])] = tuple(rec["client"])
        if not addrs:
            addrs = {n: tuple(a)
                     for n, a in self.groups[gid].addrs.items()}
        old = None
        with self._read_lock:
            st = self._read_pools.get(gid)
            if st is not None and st["addrs"] == addrs:
                st["at"] = now  # membership unchanged: keep the conns
                return st["client"], st["order"]
            client = ClusterClient(addrs)
            if st is not None:
                old = st["client"]
            self._read_pools[gid] = {
                "addrs": addrs, "client": client,
                "order": sorted(addrs), "at": now}
        if old is not None:
            old.close()
        return client, sorted(addrs)

    def _be_query(self, gid: int, q: str, variables,
                  ctx, tenant: str) -> dict:
        """Watermark-bounded follower read: one shared read_ts, tried
        round-robin across the group's replicas; StaleRead (replica's
        applied watermark behind the grant) or an unreachable replica
        rotates to the next one, and when EVERY replica fails the read
        falls back to the leader-routed pinned read at the same
        read_ts — which always qualifies (barrier + reconcile), so a
        best-effort read degrades in latency, never in consistency."""
        read_ts = self._granted_read_ts()
        client, order = self._read_pool(gid)
        with self._read_lock:
            start = self._rr
            self._rr += 1
        for i in range(len(order)):
            node = order[(start + i) % len(order)]
            if ctx is not None:
                ctx.check(f"follower read at node {node}")
            try:
                return client.query_at(
                    node, q, variables, read_ts=read_ts,
                    deadline_ms=ctx.remaining_ms() if ctx else None,
                    tenant=tenant)
            except (StaleRead, ConnectionError):
                continue
        return self.groups[gid].query(
            q, variables, read_ts=read_ts,
            deadline_ms=ctx.remaining_ms() if ctx else None)

    def _federated_query(self, q: str, variables: Optional[dict],
                         full_tmap: dict, ctx=None) -> dict:
        from dgraph_tpu.cluster.federated import FederatedDB

        tmap = full_tmap["tablets"]
        splits = full_tmap.get("splits", {})
        read_ts = self.zero.assign_ts(1)
        fdb = FederatedDB(self.groups, tmap, "", read_ts, ctx=ctx,
                          splits=splits)
        # schema from every group: on-the-fly predicates exist only on
        # their owning group, so no single group has the whole picture
        for gid in sorted(self.groups):
            try:
                text = fdb._task(gid, {"op": "task",
                                       "kind": "schema_state",
                                       "read_ts": read_ts})
                if text:
                    fdb.schema.apply_text(text)
            except RuntimeError:
                continue  # group down: its tablets will error if used
        out = fdb.query(q, variables)
        out.setdefault("extensions", {})["federated"] = True
        out["extensions"]["read_ts"] = read_ts
        touched = {p: {"owners": [int(g) for g in
                                  splits[p]["owners"]]}
                   for p in splits
                   if p in fdb.tablets.keys()}  # instantiated only
        if touched:
            # EXPLAIN-adjacent visibility: which sub-tablet fan-outs
            # served this query (mirrors zero /state `splits`)
            out["extensions"]["splitRouting"] = touched
        return out

    def _scatter_query(self, q: str, variables: Optional[dict],
                       parsed, full_tmap: dict, ctx=None) -> dict:
        from dgraph_tpu.server.acl import block_predicates

        tmap = full_tmap["tablets"]
        splits = full_tmap.get("splits", {})
        # assign each top-level block to its owning group; blocks
        # sharing variables must land on ONE group (a var defined in
        # group A cannot feed a block served by group B)
        var_home: dict[str, int] = {}
        assign: list[tuple[int, Any]] = []
        for gq in parsed.queries:
            bpreds = {p.lstrip("~") for p in block_predicates(gq)}
            if any(p in splits for p in bpreds):
                # a split predicate's rows span groups within ONE
                # block: only the federated fan-out can union them
                raise _NeedsFederation(gq.alias)
            owners = {tmap[p] for p in bpreds if p in tmap}
            if len(owners) > 1:
                raise _NeedsFederation(gq.alias)
            gid = owners.pop() if owners else min(self.groups)
            for vc in self._block_var_uses(gq):
                home = var_home.get(vc)
                if home is not None and home != gid:
                    raise _NeedsFederation(vc)
                var_home[vc] = gid
            assign.append((gid, gq))

        # one zero-issued GLOBAL timestamp pins every group's MVCC
        # snapshot: the scatter reads a single consistent cut of the
        # cluster (groups share zero's ts order, so "commits <= T"
        # means the same instant everywhere)
        read_ts = self.zero.assign_ts(1)
        # the full document runs on every involved group (var chains
        # assigned to that group resolve completely there); each
        # block's RESULT is taken from its owning group only
        merged: dict = {"data": {},
                        "extensions": {"scatter": [],
                                       "read_ts": read_ts}}
        for gid in sorted({g for g, _ in assign}):
            if ctx is not None:
                ctx.check(f"scatter to group {gid}")
            out = self.groups[gid].query(
                q, variables, read_ts=read_ts,
                deadline_ms=ctx.remaining_ms() if ctx else None)
            data = out.get("data", {})
            # response shape must not depend on tablet placement:
            # carry extensions like the single-group path does
            for k, v in out.get("extensions", {}).items():
                merged["extensions"].setdefault(k, v)
            merged["extensions"]["scatter"].append(gid)
            for g, gq in assign:
                if g != gid or gq.alias == "var":
                    continue
                key = gq.alias
                if key in data:
                    merged["data"][key] = data[key]
                if gq.attr == "shortest" and "_path_" in data:
                    merged["data"]["_path_"] = data["_path_"]
        return merged

    @staticmethod
    def _block_var_uses(gq) -> set[str]:
        """Every variable a block defines or consumes — including
        filter trees, shortest from/to, expand(var), math trees and
        facet vars; missing any of these would let a cross-group var
        slip past the guard and silently resolve empty."""
        names = set()

        def walk_filter(ft):
            if ft is None:
                return
            if ft.func is not None:
                for vc in ft.func.needs_var:
                    names.add(vc.name)
            for c in ft.children:
                walk_filter(c)

        def walk_math(mt):
            if mt is None:
                return
            if mt.var:
                names.add(mt.var)
            for c in mt.children:
                walk_math(c)

        def walk(g):
            if g.var:
                names.add(g.var)
            for vc in g.needs_var:
                names.add(vc.name)
            if g.func:
                for vc in g.func.needs_var:
                    names.add(vc.name)
            walk_filter(g.filter)
            if g.shortest is not None:
                for fn in (g.shortest.from_, g.shortest.to):
                    if fn is not None:
                        for vc in fn.needs_var:
                            names.add(vc.name)
            if getattr(g, "expand", ""):
                names.add(g.expand)  # may be a var (or _all_/a type)
            walk_math(getattr(g, "math", None))
            for v in getattr(g, "facet_var", {}).values():
                names.add(v)
            for c in g.children:
                walk(c)

        walk(gq)
        return names

    # --------------------------------------------------------- tablet move

    def move_tablet(self, pred: str, dst_group: int,
                    timeout_s: float = 60.0) -> None:
        """Live predicate move, OWNED by the Zero quorum (ref
        zero/tablet.go:62 movetablet + worker/predicate_move.go): this
        client only files the request and waits on the replicated move
        LEDGER. Zero's leader drives snapshot stream -> CDC catch-up
        -> bounded-lag fence -> ownership flip -> source drop,
        persisting each phase through its Raft group, so the move
        completes (or aborts cleanly, pre-flip) even if THIS process —
        or the Zero leader itself — dies mid-move. The source serves
        reads AND writes throughout; only the short `fenced` phase
        rejects writes to this one predicate. Concurrent movers
        serialize at the ledger: the second request returns 'already
        moving'."""
        tmap = self.tablet_map()
        src = tmap["tablets"].get(pred)
        if src is None:
            raise RuntimeError(f"tablet {pred!r} is not served anywhere")
        if src == dst_group:
            return
        resp = self.zero.request({"op": "move_request",
                                  "args": (pred, dst_group)})
        if not resp.get("ok") or not resp.get("result"):
            raise RuntimeError(
                f"tablet {pred!r} move refused: "
                f"{resp.get('error', 'already moving?')}")
        self._await_move(pred, dst_group, timeout_s)

    def split_tablet(self, pred: str, dst_group: int,
                     nshards: int = 2, shard: Optional[int] = None,
                     timeout_s: float = 60.0) -> None:
        """Split a hot predicate into `nshards` hash-range sub-tablets
        by moving `shard` (default: the last one) onto `dst_group` —
        same crash-safe phase machine as move_tablet; after the flip
        the routing map carries a `splits` entry and reads fan out
        (cluster/federated.py), writes route per subject uid."""
        shard = nshards - 1 if shard is None else int(shard)
        resp = self.zero.request(
            {"op": "move_request",
             "args": (pred, dst_group, int(nshards), shard)})
        if not resp.get("ok") or not resp.get("result"):
            raise RuntimeError(
                f"tablet {pred!r} split refused: "
                f"{resp.get('error', 'already moving/split?')}")
        self._await_move(pred, dst_group, timeout_s, split=True)

    def _await_move(self, pred: str, dst_group: int, timeout_s: float,
                    split: bool = False) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                tmap = self.tablet_map()
            except RuntimeError:
                time.sleep(0.3)  # zero election in progress
                continue
            if pred not in tmap.get("moves", {}):
                if split:
                    ent = tmap.get("splits", {}).get(pred)
                    if ent and int(dst_group) in \
                            {int(g) for g in ent["owners"]}:
                        return
                elif tmap["tablets"].get(pred) == dst_group:
                    return
                raise RuntimeError(
                    f"tablet {pred!r} move aborted by zero "
                    f"(owner is group {tmap['tablets'].get(pred)})")
            time.sleep(0.2)
        raise TimeoutError(
            f"tablet {pred!r} move still in flight after {timeout_s}s "
            "(zero keeps driving it; check tablet_map later)")

    def abort_move(self, pred: str, dst_group: int) -> bool:
        """Abort an in-flight move without flipping ownership — the
        operator escape hatch. Refused (False) once the move has
        flipped: the destination then owns the only routed copy. On a
        successful pre-flip abort the destination's staged/installed
        copy is dropped too — the streaming path installs the copy
        long before the flip, and leaving it would strand a stale
        orphan whose size/heat reports skew the rebalancer."""
        resp = self.zero.request({"op": "tablet_move_abort",
                                  "args": (pred, dst_group)})
        ok = bool(resp.get("ok") and resp.get("result"))
        if ok and dst_group in self.groups:
            try:
                self.groups[dst_group].request(
                    {"op": "drop_tablet", "pred": pred})
            except Exception:  # noqa: BLE001 — best-effort cleanup  # dglint: disable=DG07 (abort cleanup is best-effort BY CONTRACT)
                pass
        return ok

    def close(self):
        self.zero.close()
        for c in self.groups.values():
            c.close()
        with self._read_lock:
            pools = [st["client"] for st in self._read_pools.values()]
            self._read_pools.clear()
        for c in pools:
            c.close()


class Rebalancer:
    """Periodic tablet rebalancing (ref zero/tablet.go:62
    rebalanceTablets, default every 8 minutes): each tick compares
    group loads and live-moves ONE tablet from the heaviest group to
    the least loaded, converging the cluster a step at a time exactly
    like the reference (chooseTablet moves one predicate per cycle so
    a bad heuristic can never thrash the whole keyspace at once).

    Load = tablet count by default; pass size_fn(pred) for a
    byte-weighted choice (the reference weighs by tablet space from
    membership reports)."""

    def __init__(self, cluster: RoutedCluster,
                 interval_s: float = 480.0, threshold: int = 2,
                 size_fn=None, use_reported: bool = None):
        import threading
        self.cluster = cluster
        self.interval_s = interval_s
        self.threshold = threshold
        self.size_fn = size_fn or (lambda pred: 1)
        # honor the alphas' byte reports only when the caller's
        # threshold is byte-scale (mixing byte weights with a
        # tablet-count threshold would move on a 2-byte spread)
        self.use_reported = (threshold > 4096) \
            if use_reported is None else use_reported
        self.moves: list[tuple[str, int, int]] = []
        self._stop = threading.Event()
        self._thread: Optional[Any] = None

    def tick(self) -> Optional[tuple[str, int, int]]:
        """One rebalance pass; returns the move made, if any."""
        tmap = self.cluster.tablet_map()
        by_group: dict[int, list[str]] = {
            g: [] for g in self.cluster.groups}
        for pred, gid in tmap["tablets"].items():
            if pred in tmap["moving"] or pred.startswith("dgraph."):
                continue
            by_group.setdefault(gid, []).append(pred)
        # byte weights from the alphas' periodic size reports when
        # zero has them (ref zero/tablet.go:180); explicit size_fn or
        # count otherwise
        reported = tmap.get("sizes", {}) if self.use_reported else {}

        def weigh(pred: str) -> int:
            got = reported.get(pred)
            # 0 is a legitimate report (emptied tablet), not "missing"
            return int(got) if got is not None else self.size_fn(pred)

        load = {g: sum(weigh(p) for p in ps)
                for g, ps in by_group.items()}
        heavy = max(sorted(load), key=lambda g: load[g])
        light = min(sorted(load), key=lambda g: load[g])
        if load[heavy] - load[light] < self.threshold \
                or not by_group[heavy]:
            return None
        # smallest tablet that still helps — the move must STRICTLY
        # shrink the pair's spread, else a big tablet just mirrors the
        # imbalance and the next tick moves it straight back, an
        # export/import oscillation forever (ref chooseTablet walks
        # candidates until the move improves the spread)
        spread = load[heavy] - load[light]
        for pred in sorted(by_group[heavy],
                           key=lambda p: (weigh(p), p)):
            sz = weigh(pred)
            if abs((load[heavy] - sz) - (load[light] + sz)) < spread:
                self.cluster.move_tablet(pred, light)
                move = (pred, heavy, light)
                self.moves.append(move)
                return move
        return None

    def start(self):
        import threading

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — keep rebalancing  # dglint: disable=DG07 (rebalancer daemon; no request context flows here)
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
