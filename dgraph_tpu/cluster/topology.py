"""Multi-group topology: predicate-sharded groups behind one client.

The reference shards data by PREDICATE across Alpha groups: Zero owns
the tablet->group map (zero/tablet.go), alphas serve only their
tablets, queries/mutations route per predicate (worker/groups.go
BelongsTo, worker/task.go:131 attr routing), and the rebalancer moves
tablets between groups (zero/tablet.go:62 movetablet,
worker/predicate_move.go). RoutedCluster is that tier's client side:
it consults the replicated Zero quorum for ownership, claims unowned
predicates on first write (least-loaded group), refuses writes to
tablets mid-move, and orchestrates live tablet moves
(export -> import -> flip -> drop).

Round-2 scope note: a single request's predicates must resolve to ONE
group (cross-group joins — the reference's scatter-gather across
groups — stay on the roadmap; the storage/move/routing substrate here
is what they build on).
"""

from __future__ import annotations

from typing import Optional

from dgraph_tpu.cluster.client import ClusterClient


class RoutedCluster:
    def __init__(self, zero: ClusterClient,
                 groups: dict[int, ClusterClient]):
        self.zero = zero
        self.groups = dict(groups)

    # ------------------------------------------------------------- routing

    def tablet_map(self) -> dict:
        resp = self.zero.request({"op": "tablet_map"})
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "zero unreachable"))
        return resp["result"]

    def _preds_of_query(self, q: str, variables=None) -> set[str]:
        from dgraph_tpu.gql import parse
        from dgraph_tpu.server.acl import query_predicates
        return {p.lstrip("~") for p in
                query_predicates(parse(q, variables))}

    def _preds_of_mutation(self, kw: dict) -> set[str]:
        from dgraph_tpu.server.acl import (
            nquad_predicates, query_predicates,
        )
        preds = set(nquad_predicates(
            kw.get("set_nquads", ""), kw.get("del_nquads", ""),
            kw.get("set_json"), kw.get("delete_json")))
        if kw.get("query"):
            from dgraph_tpu.gql import parse
            preds |= set(query_predicates(
                parse(kw["query"], kw.get("variables"))))
        return {p.lstrip("~") for p in preds if p != "*"}

    def _group_for(self, preds: set[str], claim: bool) -> int:
        """Resolve the single group serving `preds`; with claim=True,
        unowned predicates are claimed for the chosen group (ref
        zero.go ShouldServe: first writer claims the tablet)."""
        tmap = self.tablet_map()
        moving = tmap["moving"]
        for p in preds:
            if p in moving:
                raise RuntimeError(
                    f"tablet {p!r} is being moved; retry shortly")
        owners = {tmap["tablets"][p] for p in preds
                  if p in tmap["tablets"]}
        if len(owners) > 1:
            raise RuntimeError(
                f"predicates {sorted(preds)} span groups "
                f"{sorted(owners)}; cross-group requests are not "
                "supported yet")
        unowned = [p for p in preds if p not in tmap["tablets"]]
        if owners:
            gid = owners.pop()
        elif not unowned:
            gid = min(self.groups)  # no predicates at all (uid-only)
        else:
            # least-loaded group by tablet count (the rebalancer's
            # heuristic inverted: place new tablets where it's empty)
            counts = {g: 0 for g in self.groups}
            for owner in tmap["tablets"].values():
                if owner in counts:
                    counts[owner] += 1
            gid = min(sorted(counts), key=lambda g: counts[g])
        if claim:
            for p in unowned:
                got = self.zero.tablet(p, gid)
                if got != gid:
                    raise RuntimeError(
                        f"tablet {p!r} was claimed by group {got} "
                        "concurrently; retry")
        return gid

    # ------------------------------------------------------------- surface

    def alter(self, schema_text: str = "", **kw):
        """Schema is cluster-wide: broadcast to every group (the
        reference stores schema per group for its tablets; replicating
        the full text everywhere is a superset with identical
        semantics)."""
        for gid in sorted(self.groups):
            self.groups[gid].alter(schema_text, **kw)

    def mutate(self, **kw) -> dict:
        gid = self._group_for(self._preds_of_mutation(kw), claim=True)
        return self.groups[gid].mutate(**kw)

    def query(self, q: str, variables: Optional[dict] = None) -> dict:
        preds = self._preds_of_query(q, variables)
        gid = self._group_for(preds, claim=False)
        return self.groups[gid].query(q, variables)

    # --------------------------------------------------------- tablet move

    def move_tablet(self, pred: str, dst_group: int) -> None:
        """Live predicate move (ref zero/tablet.go:62 movetablet +
        worker/predicate_move.go):

          1. zero marks the tablet read-only for the move
          2. source group leader exports the rolled-up tablet
          3. destination group imports it (replicated to its members)
          4. zero flips ownership
          5. source group drops its copy
        """
        tmap = self.tablet_map()
        src = tmap["tablets"].get(pred)
        if src is None:
            raise RuntimeError(f"tablet {pred!r} is not served anywhere")
        if src == dst_group:
            return
        resp = self.zero.request({"op": "tablet_move_start",
                                  "args": (pred, dst_group)})
        if not resp.get("ok") or not resp.get("result"):
            raise RuntimeError(
                f"tablet {pred!r} move refused: "
                f"{resp.get('error', 'already moving?')}")
        try:
            blob = self.groups[src]._unwrap(self.groups[src].request(
                {"op": "export_tablet", "pred": pred}))
            self.groups[dst_group]._unwrap(
                self.groups[dst_group].request(
                    {"op": "import_tablet", "pred": pred, "blob": blob}))
        except Exception:
            # clear the moving mark without flipping ownership —
            # writes resume against the source copy (if this also
            # fails, abort_move() is the operator escape hatch)
            self.abort_move(pred, dst_group)
            raise
        resp = self.zero.request({"op": "tablet_move_done",
                                  "args": (pred, dst_group)})
        if not resp.get("ok") or not resp.get("result"):
            # the flip did NOT commit: Zero still routes to the source,
            # so the source copy must survive — only the moving mark
            # needs clearing (the destination's orphan copy is dropped
            # best-effort)
            self.abort_move(pred, dst_group)
            try:
                self.groups[dst_group].request(
                    {"op": "drop_tablet", "pred": pred})
            except Exception:  # noqa: BLE001 — orphan copy is harmless
                pass
            raise RuntimeError(
                f"tablet {pred!r} ownership flip failed: "
                f"{resp.get('error', 'zero rejected the move')}")
        self.groups[src]._unwrap(self.groups[src].request(
            {"op": "drop_tablet", "pred": pred}))

    def abort_move(self, pred: str, dst_group: int) -> bool:
        """Clear a stuck moving mark without flipping ownership — the
        operator escape hatch when a move crashed mid-flight."""
        resp = self.zero.request({"op": "tablet_move_abort",
                                  "args": (pred, dst_group)})
        return bool(resp.get("ok") and resp.get("result"))

    def close(self):
        self.zero.close()
        for c in self.groups.values():
            c.close()
