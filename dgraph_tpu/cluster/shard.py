"""Hash-range sub-tablets: one predicate split across groups.

The reference keeps a whole predicate on one group — a viral predicate
therefore pins its group forever, the named million-user failure mode
(ROADMAP item 4). A split partitions a predicate's rows by SUBJECT
uid hash into `nshards` ranges; each range ("sub-tablet") is owned by
a group independently in Zero's routing map (`splits` next to
`tablets`), writes route per resolved subject through the existing
2PC machinery, and reads fan out to every owner and union
(cluster/federated.py SplitRemoteTablet).

The hash must be (a) stable across processes/versions — routing and
data placement both derive from it, a drifting hash silently orphans
rows — and (b) well-mixed over dense sequential uid leases (uid % n
would stripe every entity batch onto one shard). splitmix64's
finalizer is the standard choice; implemented in pure ints, masked to
64 bits.
"""

from __future__ import annotations

_M = (1 << 64) - 1


def mix64(x: int) -> int:
    """splitmix64 finalizer: deterministic 64-bit mix."""
    x = (x + 0x9E3779B97F4A7C15) & _M
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M
    return x ^ (x >> 31)


def shard_of(uid: int, nshards: int) -> int:
    """The sub-tablet index owning SUBJECT `uid` of an n-way split."""
    if nshards <= 1:
        return 0
    return mix64(int(uid)) % int(nshards)


def shard_mask(uids, nshards: int, shard: int):
    """Vectorized membership: bool mask of `uids` (ndarray) whose
    shard_of == shard. numpy splitmix64 with wrapping uint64 ops."""
    import numpy as np
    x = np.asarray(uids, np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(nshards)) == np.uint64(shard)


def filter_ops(ops, nshards: int, shard: int,
               invert: bool = False) -> list:
    """The EdgeOps of one commit that land in `shard` (subject-hash
    routing: an op belongs where its src lives). `invert` keeps the
    complement — the source's post-split prune."""
    return [op for op in ops
            if (shard_of(int(op.src), nshards) == int(shard))
            != bool(invert)]


def shard_view(tab, nshards: int, shard: int, invert: bool = False):
    """A fresh Tablet holding exactly `tab`'s rows whose SUBJECT uid
    hashes into `shard` — the unit a split move snapshots/streams.
    Derived planes (token index, reverse) rebuild from the filtered
    base so they are exactly consistent with it; the trained vector
    index is deliberately NOT carried (it covers all rows — the
    destination retrains at rollup). Unfolded overlay deltas filter
    per-op, preserving commit timestamps, so CDC catch-up offsets
    stay aligned with the full tablet's."""
    from dgraph_tpu.storage.tablet import Tablet

    inv = bool(invert)
    keep = lambda src: \
        (shard_of(int(src), nshards) == int(shard)) != inv  # noqa: E731
    out = Tablet(tab.pred, tab.schema)
    out.base_ts = tab.base_ts
    out.max_commit_ts = tab.max_commit_ts
    out.edges = {s: v.copy() for s, v in tab.edges.items() if keep(s)}
    out.values = {s: list(v) for s, v in tab.values.items() if keep(s)}
    out.edge_facets = {k: dict(v) for k, v in tab.edge_facets.items()
                       if keep(k[0])}
    out.deltas = [(ts, filter_ops(ops, nshards, shard, invert=inv))
                  for ts, ops in tab.deltas]
    out.rebuild_index()
    out.rebuild_reverse()
    return out


def owners_of(splits_entry: dict) -> list[int]:
    """The distinct owning groups of a split predicate, sorted."""
    return sorted(set(int(g) for g in splits_entry["owners"]))


def owner_for_uid(splits_entry: dict, uid: int) -> int:
    """The group serving SUBJECT `uid` of a split predicate."""
    owners = splits_entry["owners"]
    return int(owners[shard_of(int(uid), len(owners))])
