"""Networked Raft services: Alpha groups and the Zero quorum as real
processes on real sockets.

The reference runs every shard as a Raft group inside an Alpha process
(worker/draft.go Run loop pumping etcd raft Ready) and the cluster
coordinator as its own Raft quorum inside Zero (dgraph/cmd/zero/
raft.go:619, zero.go:410). This module is that tier: `RaftServer` owns
a RaftNode, a TcpTransport (cluster/transport.py), a wall-clock tick
loop and a client RPC listener; `AlphaServer` replicates a GraphDB
through it (leader executes, expanded records replicate — the
worker/mutation.go:537 MutateOverNetwork shape), `ZeroServer`
replicates the coordinator state machine (ts/uid leases + conflict
oracle — zero/assign.go, zero/oracle.go).

Client protocol: wire-framed request/response dicts. Writes must land
on the leader; a follower answers {"ok": False, "leader": id} and the
client re-dials (ref conn/pool.go + dgo's leader routing).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Optional

from dgraph_tpu import wire
from dgraph_tpu.cluster.raft import (
    FOLLOWER, GOODBYE, LEADER, Msg, RaftNode, VOTE_REQ,
)
from dgraph_tpu.cluster.errors import (
    StaleRead, TabletMisrouted, WriteFenced,
)
from dgraph_tpu.cluster.transport import TcpTransport
from dgraph_tpu.utils import failpoint, metrics, netfault, tracing
from dgraph_tpu.utils.logger import log
from dgraph_tpu.utils.reqctx import (
    PROPAGATION_SKEW_S, DeadlineExceeded, Overloaded, RequestAborted,
    RequestContext,
)

import socket


class RaftServer:
    """A Raft replica process: tick thread + transport + client RPC.

    Subclasses define the replicated state machine:
      - sm_apply(origin, payload) -> Any   (every committed entry)
      - sm_snapshot() -> Any / sm_restore(Any)
      - handle_request(req) -> dict        (client RPC dispatch)
    """

    def __init__(self, node_id: int,
                 raft_peers: dict[int, tuple[str, int]],
                 client_addr: tuple[str, int],
                 storage=None, tick_s: float = 0.05,
                 election_ticks: int = 10,
                 snapshot_every: int = 2048,
                 debug_port: int = 0,
                 debug_host: str = "127.0.0.1",
                 learner: bool = False,
                 learner_ids=()):
        self.id = node_id
        # conf-changed membership persisted in raft storage wins over
        # the CLI's --raft-peers on restart (ref zero/raft.go member
        # state living in Zero's raft group)
        saved = storage.load_members() if storage is not None else None
        self._removed_ids: set[int] = set()
        # non-voting members (raft learners): replicated to, never
        # counted toward any quorum, never campaigning — the read
        # scale-out tier (ref etcd learner members / the reference's
        # StreamMembership non-voting replicas)
        self.learner_ids: set[int] = set()
        if saved and isinstance(saved, dict) and "members" in saved:
            self.members = {int(k): tuple(v)
                            for k, v in saved["members"].items()}
            self._removed_ids = {int(x)
                                 for x in saved.get("removed", ())}
            self.learner_ids = {int(x)
                                for x in saved.get("learners", ())}
        elif saved:
            self.members = {int(k): tuple(v) for k, v in saved.items()}
        else:
            self.members = dict(raft_peers)
        if learner:
            self.learner_ids.add(node_id)
        # membership learned at join time (zero's connect reply marks
        # learner members) — persisted membership still wins above
        if not saved:
            self.learner_ids |= {int(x) for x in learner_ids}
        if node_id not in self.members and node_id in raft_peers \
                and node_id not in self._removed_ids:
            self.members[node_id] = raft_peers[node_id]
        voters = [m for m in self.members
                  if m not in self.learner_ids]
        self.node = RaftNode(node_id, voters,
                             storage=storage,
                             election_ticks=election_ticks,
                             learner=node_id in self.learner_ids)
        for lid in sorted(self.learner_ids):
            self.node.add_learner(lid)
        self.lock = threading.RLock()
        self.applied_cv = threading.Condition(self.lock)
        self.tick_s = tick_s
        self.snapshot_every = snapshot_every
        self._applied_since_snap = 0
        self._mark_seq = itertools.count(1)
        self._acked: dict[tuple, Any] = {}
        # wall clock on purpose: the epoch must differ across process
        # RESTARTS (monotonic restarts near zero every boot)
        self.epoch = int(time.time() * 1000) % (1 << 40)  # dglint: disable=DG06
        self._stop = threading.Event()
        # peer -> monotonic time a Raft message last arrived from it:
        # the operator-visible "is this peer partitioned from me" age
        # (surfaced in status/health/debug stats and tools/dgtop.py —
        # a partition is otherwise invisible from the outside until
        # something times out)
        self._last_heard: dict[int, float] = {}
        transport_peers = dict(self.members)
        if node_id in raft_peers:  # own listen addr always from CLI
            transport_peers[node_id] = raft_peers[node_id]
        self.transport = TcpTransport(node_id, transport_peers,
                                      self._on_msg)

        self._client_listener = socket.socket(
            socket.AF_INET, socket.SOCK_STREAM)
        self._client_listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._client_listener.bind(client_addr)
        self._client_listener.listen(64)
        self.client_addr = self._client_listener.getsockname()

        # trace identity: one pid lane per node in the merged Perfetto
        # view. Subclasses set a descriptive name (alpha-g1-n2) before
        # calling super().__init__; the process-global default covers
        # one-node-per-process deployments, the per-thread binding in
        # the serving loops covers in-process multi-node harnesses.
        self.node_name = getattr(self, "node_name", f"node-{node_id}")
        tracing.set_node(self.node_name)

        self._threads = [
            threading.Thread(target=self._tick_loop, daemon=True,
                             name=f"raft-tick-{node_id}"),
            threading.Thread(target=self._client_accept_loop, daemon=True,
                             name=f"client-accept-{node_id}"),
        ]

        # read-only debug/observability HTTP listener (stats, request
        # ring, Prometheus text, trace slices, sampling profiler) —
        # the reference wires its pprof/expvar mux onto every node
        # (x/metrics.go); collectors (tools/dgtop.py, tools/dgbench.py)
        # scrape it without speaking the framed wire protocol. 0 = off.
        self.debug_httpd = None
        if debug_port:
            from dgraph_tpu.server.debug_http import serve_debug
            self.debug_httpd, dport = serve_debug(
                stats_fn=self.debug_stats_payload,
                health_fn=self.health_payload,
                node_name=self.node_name,
                host=debug_host, port=debug_port)
            log.info("debug_http_listening", node=self.node_name,
                     port=dport)

        # restore-from-disk snapshot surfaces on the first ready();
        # only then open the floodgates (transport.start) so no inbound
        # message races construction
        with self.lock:
            out = self._drain_ready()
        self.transport.start()
        self._send_all(out)
        for t in self._threads:
            t.start()

    # ----------------------------------------------------------- raft side

    def _on_msg(self, msg):
        goodbye = None
        with self.lock:
            if self._stop.is_set():
                return
            self._last_heard[msg.frm] = time.monotonic()
            if msg.type == GOODBYE:
                # a member told us we were conf-removed (backstop for
                # a lost farewell append): go quiet
                if not self.node.removed:
                    log.info("raft_removed_notice", node=self.id,
                             frm=msg.frm)
                self.node.removed = True
                self.node.role = FOLLOWER
                self.node.leader_id = None
                return
            if msg.frm in self._removed_ids:
                # TOMBSTONED ex-members must not disturb the cluster
                # (their election timeouts would otherwise inflate
                # terms forever); tell them why so they go quiet.
                # Unknown ids are NOT dropped — an in-progress joiner
                # whose conf-add this node hasn't applied yet may need
                # to campaign to heal a leader loss (vote quorum stays
                # safe: only conf members' votes count).
                if msg.type == VOTE_REQ:
                    goodbye = Msg(GOODBYE, self.id, msg.frm,
                                  self.node.term)
            else:
                self.node.step(msg)
            out = self._drain_ready()
        if goodbye is not None:
            out.append(goodbye)
        self._send_all(out)

    def _tick_loop(self):
        tracing.set_thread_node(self.node_name)
        while not self._stop.wait(self.tick_s):
            with self.lock:
                self.node.tick()
                out = self._drain_ready()
            self._send_all(out)

    def _drain_ready(self) -> list:
        """Apply committed state under the lock; RETURN outbound msgs.
        Sends happen outside the lock — a TCP dial to a dead peer can
        block ~1s, and stalling ticks that long would trip healthy
        followers' election timers."""
        r = self.node.ready()
        if r.soft_state != getattr(self, "_soft_state", None):
            self._soft_state = r.soft_state
            log.info("raft_soft_state", node=self.id,
                     role=r.soft_state[0], leader=r.soft_state[1],
                     term=self.node.term)
        if r.snapshot is not None:
            # chaos seam: an armed `snapshot.install` failpoint delays
            # or fails the install — an error action models the apply
            # path dying mid-install (the node wedges, like a crash)
            failpoint.fire("snapshot.install")
            log.info("raft_snapshot_restore", node=self.id,
                     index=r.snapshot[0])
            data = r.snapshot[2]
            if isinstance(data, dict) and "__members__" in data:
                # snapshots carry membership so a late joiner that
                # never saw the conf entries still learns the cluster
                self._install_members(data["__members__"],
                                      data.get("__removed__", ()),
                                      data.get("__learners__", ()))
                data = data["app"]
            self.sm_restore(data)
            self._acked.clear()
        if r.committed:
            # one span per committed batch (not per entry): the
            # request thread's propose_and_wait drains here, so a
            # traced write's apply cost shows inside its trace; tick-
            # thread applies self-root under this node's lane
            with tracing.span("raft.apply", n=len(r.committed)):
                for e in r.committed:
                    if e.data is None:
                        continue
                    mark, origin, payload = e.data
                    if isinstance(payload, tuple) and payload \
                            and payload[0] == "__conf__":
                        result = self._apply_conf(*payload[1:])
                    else:
                        result = self.sm_apply(origin, payload)
                    self._acked[mark] = result
                    self._applied_since_snap += 1
                    self.applied_cv.notify_all()
            # committed-applied distance AFTER an apply batch (not on
            # every message: the gauge write is off the heartbeat hot
            # path this way) — the watchdog's raft_apply_lag rule and
            # Prometheus both read it
            metrics.set_gauge(
                "dgraph_raft_apply_lag",
                max(0, self.node.commit_index
                    - self.node.applied_index),
                labels={"node": getattr(self, "node_name",
                                        f"node-{self.id}")})
        if self._applied_since_snap >= self.snapshot_every:
            self._applied_since_snap = 0
            self.node.take_snapshot(
                {"__members__": dict(self.members),
                 "__removed__": sorted(self._removed_ids),
                 "__learners__": sorted(self.learner_ids),
                 "app": self.sm_snapshot()})
        return r.msgs

    # ------------------------------------------------------- membership
    # Single-change-at-a-time conf changes applied at commit (the etcd
    # model; ref conn/raft_server.go JoinCluster + zero /removeNode).

    def _install_members(self, members: dict, removed=(), learners=()):
        members = {int(k): tuple(v) for k, v in members.items()}
        for nid, addr in members.items():
            if nid != self.id:
                self.transport.peers[nid] = addr
        self.members = members
        self._removed_ids = {int(x) for x in removed}
        self.learner_ids = {int(x) for x in learners
                            if int(x) in members}
        for nid in list(self.node.peers) + sorted(self.node.learners):
            if nid not in members:
                self.node.remove_peer(nid)
        for nid in members:
            if nid == self.id:
                continue
            if nid in self.learner_ids:
                self.node.add_learner(nid)
            else:
                self.node.add_peer(nid)
        if self.id not in members:
            self.node.remove_peer(self.id)
        elif self.id in self.learner_ids:
            self.node.add_learner(self.id)
        self._save_members()

    def _apply_conf(self, action: str, nid: int, addr=None) -> bool:
        nid = int(nid)
        if action == "add":
            if addr is None:
                return False
            self.members[nid] = tuple(addr)
            self.learner_ids.discard(nid)  # promotion keeps progress
            if nid != self.id:
                self.transport.peers[nid] = tuple(addr)
            self.node.add_peer(nid)
        elif action == "add_learner":
            # non-voting join: the learner receives the replicated log
            # (and this very conf entry) but never joins any quorum
            if addr is None:
                return False
            self.members[nid] = tuple(addr)
            self.learner_ids.add(nid)
            if nid != self.id:
                self.transport.peers[nid] = tuple(addr)
            self.node.add_learner(nid)
        elif action == "remove":
            self.members.pop(nid, None)
            if nid != self.id and self.node.role == LEADER \
                    and nid in self.node.peers:
                # farewell append BEFORE forgetting the peer: it
                # carries the commit index covering this removal, so
                # the leaving node applies it, learns it was removed,
                # and goes quiet instead of campaigning forever
                # (review finding: the commit otherwise never reaches
                # it). The transport keeps its address so the queued
                # message can still be delivered; a lost farewell is
                # backstopped by GOODBYE notices.
                self.node._send_append(nid)
            self.node.remove_peer(nid)
            self.learner_ids.discard(nid)
        else:
            return False
        if action in ("add", "add_learner"):
            self._removed_ids.discard(nid)
        else:
            self._removed_ids.add(nid)
        log.info("raft_conf_change", node=self.id, action=action,
                 member=nid, members=sorted(self.members))
        self._save_members()
        return True

    def _save_members(self):
        if self.node.storage is not None:
            self.node.storage.save_members(
                {"members": dict(self.members),
                 "removed": sorted(self._removed_ids),
                 "learners": sorted(self.learner_ids)})

    def _conf_in_flight(self) -> bool:
        """One membership change at a time (raft §4.1 single-server
        rule): reject a new one while any conf entry is unapplied."""
        for e in self.node.log:
            if e.index <= self.node.applied_index or e.data is None:
                continue
            payload = e.data[2]
            if isinstance(payload, tuple) and payload \
                    and payload[0] == "__conf__":
                return True
        return False

    def handle_conf_request(self, req: dict) -> dict:
        """Shared cluster ops every RaftServer kind answers; returns
        None for ops the subclass should handle."""
        op = req.get("op")
        if op == "members":
            with self.lock:
                return {"ok": True, "result": {
                    "members": {str(k): list(v)
                                for k, v in self.members.items()},
                    "learners": sorted(self.learner_ids),
                    "removed": self.node.removed}}
        if op == "fault":
            # live control of THIS node's outbound fault table
            # (utils/netfault.py) — the wire half of the chaos plane's
            # control surface (POST /debug/fault is the HTTP half).
            # tools/dgchaos.py arms partitions/delay storms with it
            # and heals them with {"action": "clear"}.
            try:
                return {"ok": True,
                        "result": netfault.handle_control(req)}
            except (ValueError, KeyError, TypeError) as e:
                return {"ok": False,
                        "error": f"bad fault control: {e}"}
        if op == "traces":
            # node-local trace slice (the wire analogue of HTTP
            # /debug/traces?trace_id=): tools/trace_merge.py stitches
            # slices from every node into one Perfetto timeline. The
            # node filter matters for in-process multi-node harnesses,
            # where several logical nodes share one span ring.
            want = req.get("trace")
            spans = tracing.spans_for(want) if want \
                else tracing.recent_spans(int(req.get("limit", 512)))
            spans = [s for s in spans
                     if s.get("node") == self.node_name]
            return {"ok": True, "result": {"node": self.node_name,
                                           "spans": spans}}
        if op == "pprof":
            # on-demand wall-clock sampling profile of THIS process
            # (the wire analogue of HTTP /debug/pprof, same payload):
            # seconds=/hz=/format= ride the request dict. Blocks the
            # serving connection for the window — by contract — but
            # never the raft lock: sampling is lock-free.
            from dgraph_tpu.utils import pprof
            return {"ok": True, "result": pprof.handle_params(
                {k: req[k] for k in ("seconds", "hz", "format")
                 if k in req},
                node=self.node_name)}
        if op == "metrics_text":
            # Prometheus text exposition over the cluster wire, for
            # collectors (tools/dgbench.py) scraping nodes that run
            # without the HTTP debug listener
            from dgraph_tpu.utils import metrics
            return {"ok": True,
                    "result": {"node": self.node_name,
                               "text": metrics.render_prometheus()}}
        if op == "alerts":
            # the alerting plane over the cluster wire (the analogue
            # of HTTP /debug/alerts): rule catalog + firing set +
            # recent transitions, with operator controls riding the
            # request dict (ack=<series>, silence=<series> +
            # silence_s=<ttl>). Zero's override adds the cluster-wide
            # aggregation of piggybacked alpha alerts.
            from dgraph_tpu.utils import watchdog
            if req.get("ack"):
                return {"ok": True, "result": {
                    "acked": watchdog.ack(str(req["ack"]))}}
            if req.get("silence"):
                watchdog.silence(str(req["silence"]),
                                 float(req.get("silence_s", 3600.0)))
                return {"ok": True, "result": {"silenced": True}}
            out = watchdog.alerts_payload()
            out["node"] = self.node_name
            out.update(self._alerts_extra())
            return {"ok": True, "result": out}
        if op == "incidents":
            # the flight recorder's bundle ring (the analogue of HTTP
            # /debug/incidents): manifests, or one full bundle by id
            from dgraph_tpu.utils import watchdog
            try:
                out = watchdog.incidents_payload(
                    limit=int(req.get("limit", 16)),
                    bundle=req.get("id"))
            except KeyError as e:
                return {"ok": False, "error": str(e)}
            out["node"] = self.node_name
            return {"ok": True, "result": out}
        if op == "conf_change":
            action = req.get("action")
            nid = int(req.get("node", 0))
            addr = req.get("addr")
            if action not in ("add", "add_learner", "remove") \
                    or not nid:
                return {"ok": False, "error": "bad conf_change"}
            if action in ("add", "add_learner") and not addr:
                return {"ok": False, "error": f"{action} needs addr"}
            def gate():
                # checked under the SAME lock as the propose: two
                # racing conf_change RPCs must not both slip past the
                # single-change-in-flight rule (review finding)
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                if self._conf_in_flight():
                    return "another membership change is in flight"
                return None

            ok, result = self.propose_and_wait(
                ("__conf__", action, nid,
                 tuple(addr) if addr else None), gate=gate)
            if not ok:
                return {"ok": False, "error":
                        result if isinstance(result, str)
                        else "conf change not committed"}
            if not result:
                return {"ok": False,
                        "error": "conf change not committed"}
            with self.lock:
                members = {str(k): list(v)
                           for k, v in self.members.items()}
            return {"ok": True, "result": {"members": members}}
        return None

    def _send_all(self, msgs: list):
        for m in msgs:
            self.transport.send(m)

    def propose_and_wait(self, payload: Any,
                         timeout: float = 5.0,
                         gate=None) -> tuple[bool, Any]:
        """Propose on this node (must be leader); wait until the entry
        applies locally. -> (committed, apply result). `gate`, when
        given, runs under the SAME lock as the propose and aborts it
        by returning an error string — check-then-propose sequences
        (the one-conf-change-in-flight rule) need that atomicity."""
        mark = (self.id, self.epoch, next(self._mark_seq))
        with self.lock:
            if gate is not None:
                err = gate()
                if err:
                    return False, err
            if not self.node.propose((mark, (self.id, self.epoch),
                                      payload)):
                return False, None
            out = self._drain_ready()
        self._send_all(out)
        with self.lock:
            deadline = time.monotonic() + timeout
            while mark not in self._acked:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return False, None
                self.applied_cv.wait(remaining)
            return True, self._acked[mark]

    def is_leader(self) -> bool:
        with self.lock:
            return self.node.role == LEADER

    def leader_hint(self) -> Optional[int]:
        with self.lock:
            return self.node.leader_id

    # --------------------------------------------------------- client side

    def _client_accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._client_listener.accept()
            except OSError:
                return
            threading.Thread(target=self._client_loop, args=(conn,),
                             daemon=True).start()

    def _serve_traced(self, req: dict) -> dict:
        """handle_request under the caller's trace: a request carrying
        `trace_id` (attached by ClusterClient from its bound context)
        gets an `rpc.recv` span on THIS node, parented to the caller's
        rpc.send span across the wire — the hop every federated task,
        follower redirect and 2PC fan-out shows up as in the merged
        timeline."""
        tid = req.get("trace_id", "")
        if not tid or not tracing.enabled():
            return self.handle_request(req)
        with tracing.bind(tid, req.get("parent_span", ""),
                          node=self.node_name), \
                tracing.span("rpc.recv", op=str(req.get("op", ""))):
            return self.handle_request(req)

    # client-facing ops whose FAILURES the wire edge records into the
    # request log. Only ops whose SUCCESSES the engine also records
    # (db.py _query_metrics / mutate) belong here: an op with
    # failure-only recording would build an all-bad SLO series that
    # fires during a fault and then starves below min_volume, holding
    # the alert forever. Inner 2PC/task failures surface as query/
    # mutate failures at the coordinator anyway. Routing signals —
    # NotLeader/misroute/stale/fenced — are retries, not failures,
    # and must not burn SLO budget.
    _SLO_OPS = frozenset({"query", "mutate"})

    def _log_wire_failure(self, req: dict, exc: BaseException,
                          t0: float) -> None:
        op = str(req.get("op", ""))
        if op not in self._SLO_OPS:
            return
        from dgraph_tpu.utils import reqlog
        reqlog.record(
            op, trace_id=str(req.get("trace_id", "")),
            latency_ms=(time.perf_counter() - t0) * 1e3,
            outcome=reqlog.outcome_of(exc),
            tenant=str(req.get("tenant") or ""))

    def _client_loop(self, conn: socket.socket):
        tracing.set_thread_node(self.node_name)
        try:
            while not self._stop.is_set():
                req = wire.loads(wire.read_frame(conn))
                t0 = time.perf_counter()
                try:
                    resp = self._serve_traced(req)
                except NotLeader as e:
                    resp = {"ok": False, "error": "not leader",
                            "leader": e.leader}
                except TabletMisrouted as e:
                    # typed on the wire: the router refreshes its
                    # tablet map and re-routes (bounded retries) —
                    # a post-flip stale route is never a bare 500
                    resp = {"ok": False, "error": str(e),
                            "misrouted": {"pred": e.pred,
                                          "group": e.group}}
                except StaleRead as e:
                    # typed + retryable: the router re-runs the read
                    # on another replica of the group (the leader
                    # always qualifies) — bounded staleness must
                    # degrade to a retry, never to an old snapshot
                    resp = {"ok": False, "error": str(e),
                            "stale": {"readTs": e.read_ts,
                                      "watermark": e.watermark},
                            "retryable": True}
                except WriteFenced as e:
                    # typed: the client must re-point at the active
                    # primary, not retry here (async replication —
                    # standbys and fenced old primaries refuse ALL
                    # client writes)
                    resp = {"ok": False, "error": str(e),
                            "fenced": {"phase": e.phase}}
                except RequestAborted as e:
                    # cancellation/deadline crosses the wire TYPED:
                    # ClusterClient._unwrap maps `aborted` back to the
                    # reqctx exception (so the HTTP/gRPC edges answer
                    # 408/499/429, not 500) and `retryable` marks
                    # deadline/overload for jittered-backoff loops
                    self._log_wire_failure(req, e, t0)
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}",
                            "aborted": type(e).__name__,
                            "retryable": isinstance(
                                e, (DeadlineExceeded, Overloaded))}
                except Exception as e:  # surface, don't kill the conn
                    self._log_wire_failure(req, e, t0)
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                wire.write_frame(conn, wire.dumps(resp))
        except (EOFError, OSError, wire.WireError):
            pass
        finally:
            conn.close()

    # ----------------------------------------------------------- lifecycle

    def peer_ages(self) -> dict:
        """Seconds since a Raft message last arrived from each peer
        (None = never heard since boot). A healthy link ticks at the
        heartbeat cadence, so an age of several election timeouts IS a
        partition, visible from the outside — the judge dgtop and the
        chaos report read."""
        with self.lock:
            now = time.monotonic()
            return {str(p): (round(now - self._last_heard[p], 3)
                             if p in self._last_heard else None)
                    for p in self.members if p != self.id}

    def _alerts_extra(self) -> dict:
        """Extra fields the `alerts` wire op carries for this node
        kind (zero adds the cluster-wide aggregation)."""
        return {}

    def watchdog_signals(self) -> dict:
        """Stall-watchdog signals this node kind contributes to each
        evaluator tick (utils/watchdog.py register_signals): raft
        apply lag and the quietest peer's silence age. Subclasses
        extend."""
        with self.lock:
            lag = max(0, self.node.commit_index
                      - self.node.applied_index)
        out = {"raft_apply_lag": float(lag)}
        ages = [a for a in self.peer_ages().values()
                if a is not None]
        if ages:
            out["raft_peer_silent_s"] = max(ages)
        return out

    def attach_watchdog(self, wd) -> None:
        """Register this node's signal/context providers on the
        process watchdog (cli.py `node` calls it after boot)."""
        wd.register_signals(self.node_name, self.watchdog_signals)

    def debug_stats_payload(self) -> dict:
        """What this node kind contributes to /debug/stats on the
        debug HTTP listener (counters/gauges/histograms are appended
        by the listener itself). Subclasses override."""
        from dgraph_tpu.utils import watchdog
        return {"node": self.node_name,
                "netfault": netfault.rules(),
                "lastHeard": self.peer_ages(),
                "alerts": watchdog.firing_summary()}

    def health_payload(self) -> dict:
        with self.lock:
            out = {"id": self.id, "role": self.node.role,
                   "leader": self.node.leader_id,
                   "term": self.node.term,
                   "learner": self.node.learner}
        out["lastHeard"] = self.peer_ages()
        return out

    def close(self):
        self._stop.set()
        self.transport.close()
        if self.debug_httpd is not None:
            self.debug_httpd.shutdown()
            self.debug_httpd.server_close()  # shutdown() only stops
            # the loop; close the bound socket too or every closed
            # node leaks one fd + one port
        try:
            self._client_listener.close()
        except OSError:
            pass
        with self.lock:
            self.applied_cv.notify_all()

    def serve_forever(self):
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            self.close()

    # ----------------------------------------------- state machine (abstract)

    def sm_apply(self, origin, payload) -> Any:
        raise NotImplementedError

    def sm_snapshot(self) -> Any:
        raise NotImplementedError

    def sm_restore(self, snap: Any) -> None:
        raise NotImplementedError

    def handle_request(self, req: dict) -> dict:
        raise NotImplementedError


class NotLeader(Exception):
    def __init__(self, leader: Optional[int]):
        super().__init__("not leader")
        self.leader = leader


class AlphaServer(RaftServer):
    """A replicated GraphDB group member (the worker/draft.go role).

    Writes execute on the leader's engine — allocating ts/uids and
    producing expanded commit records via the on_record sink — then each
    record replicates through Raft; followers apply it verbatim
    (worker/mutation.go expand-then-propose shape). If quorum is lost
    mid-write the leader rebuilds its engine from the committed event
    stream so it never serves un-replicated state.
    """
    # dglint: guarded-by=db:atomic (the binding is REBOUND only by
    # the raft-apply path — sm_restore/_rebuild_from_events, under
    # RaftServer.lock — and the swap of the reference itself is
    # GIL-atomic; readers grab the binding once and tolerate serving
    # from either the pre- or post-restore engine, the same contract
    # a snapshot install gives the reference's workers)

    def __init__(self, node_id: int, raft_peers, client_addr,
                 storage=None, db_kw: Optional[dict] = None,
                 group: int = 1, replicas: int = 1,
                 zero_addrs: Optional[dict] = None,
                 snapshot: str = "", max_pending: int = 0,
                 learner: bool = False,
                 tenant_rate: float = 0.0,
                 tenant_burst: float = 0.0, **kw):
        from dgraph_tpu.engine.db import GraphDB

        # admission control on the wire surface (the cluster analogue
        # of the HTTP edge's --max-pending): a bounded in-flight count
        # over the work-bearing ops; excess load sheds TYPED
        # (Overloaded -> `aborted` on the wire -> the caller's 429
        # class) instead of queueing unboundedly on the serving locks.
        # 0 = unbounded.
        self.max_pending = max_pending
        self._admission = threading.Lock()
        self._inflight = 0
        # per-tenant QoS layered UNDER max_pending: one hot tenant
        # exhausts its own token bucket and degrades to typed 429s
        # while the shared in-flight budget stays available to the
        # rest (server/qos.py)
        self.qos = None
        if tenant_rate > 0:
            from dgraph_tpu.server.qos import TenantQos
            self.qos = TenantQos(rate=tenant_rate, burst=tenant_burst)
        # non-voting read replica (raft learner): never campaigns or
        # serves writes; joins its group via the add_learner conf
        # change and serves watermark-bounded follower reads
        self.learner = learner

        # group=0 + a zero quorum = elastic join (ref zero/zero.go:410
        # Connect): zero assigns this node to the least-replicated
        # group (or founds a new one), hands back the group's members,
        # and the node raft-joins them live
        self._join_members: dict = {}
        if group == 0:
            if not zero_addrs:
                raise ValueError("--group 0 (auto) needs --zero")
            from dgraph_tpu.cluster.client import ClusterClient
            probe = ClusterClient(zero_addrs, timeout=30.0)
            try:
                my_raft = tuple(raft_peers[node_id])
                got = probe.request({
                    "op": "connect",
                    "args": (f"{my_raft[0]}:{my_raft[1]}", 0, 0,
                             my_raft, tuple(client_addr),
                             int(replicas), int(bool(learner)))},
                    deadline_s=60.0)
                if not got.get("ok"):
                    raise RuntimeError(
                        f"zero connect failed: {got.get('error')}")
                asg = got["result"]
            finally:
                probe.close()
            group = asg["group"]
            node_id = asg["id"]
            raft_peers = {int(i): tuple(m["raft"])
                          for i, m in asg["members"].items()}
            raft_peers[node_id] = my_raft
            # existing learners must not be mistaken for voters (a
            # candidate counting them in its quorum could never win)
            kw.setdefault("learner_ids", tuple(
                int(i) for i, m in asg["members"].items()
                if m.get("learner") and int(i) != node_id))
            # conf changes land on the group LEADER: learners never
            # lead, so they are not join targets
            self._join_members = {
                int(i): tuple(m["client"])
                for i, m in asg["members"].items()
                if int(i) != node_id and not m.get("learner")}

        self.group = group
        self._db_kw = dict(db_kw or {})
        self._db_kw.setdefault("prefer_device", False)
        # zero-issued global read timestamps are in flight here: lag
        # background folds so pinned readers rarely hit StaleSnapshot
        # (carried in _db_kw so sm_restore/_rebuild_from_events keep
        # it when they build a fresh engine)
        self._db_kw.setdefault("rollup_window", 512)
        self.db = GraphDB(**self._db_kw)
        # bulk-booted group: seed the engine from a `dgraph_tpu bulk
        # --reduce-shards` output BEFORE raft starts (ref handing
        # out/<i>/p to a group's alphas; every replica of the group
        # must boot from the same snapshot file)
        self._boot_snapshot = snapshot
        if snapshot:
            from dgraph_tpu.storage.snapshot import load_snapshot
            load_snapshot(snapshot, self.db)
        # open interactive txns (dgo flow): leader-local by design —
        # the reference's txns are likewise coordinated with the group
        # leader and die on leader change (clients retry)
        self._txns: dict[int, Any] = {}
        self._txn_touched: dict[int, float] = {}
        # stage time of replicated cross-group fragments, for TTL-based
        # reconciliation against zero's decision registry
        self._xstage_touched: dict[int, float] = {}
        # negative txn_status cache: start_ts -> highest read_ts the
        # txn was verified UNDECIDED for. A txn undecided at check time
        # can only commit with a commit_ts issued after the check, so
        # any read_ts obtained before it stays clean — pinned reads and
        # federated tasks skip the zero RPC below that watermark.
        self._xstatus_clean: dict[int, int] = {}
        # live tablet-move plumbing (leader-local, deliberately NOT
        # replicated — both sides are rebuilt idempotently when a
        # leader dies): source-side cached export blobs served in
        # re-deliverable chunks, destination-side chunk staging
        # buffers assembled by move_install. pred -> dict.
        self._move_exports: dict[str, dict] = {}
        self._move_staging: dict[str, dict] = {}
        # last touches count reported to zero per tablet (the heat
        # report ships DELTAS); baseline-initialized on first sight so
        # a fresh leader's lifetime counter doesn't land as one spike.
        # dglint: guarded-by=_heat_sent:single-thread (only touched by
        # the one _report_sizes_loop daemon; the boot paths that could
        # each spawn it are mutually exclusive)
        self._heat_sent: dict[str, int] = {}
        # multi-group mode: a Zero quorum owns the tablet map and the
        # uid space; this alpha claims tablets, checks ownership before
        # every write, and leases uid blocks (ref worker/groups.go
        # BelongsTo + zero/assign.go lease blocks)
        self.zero = None
        if zero_addrs:
            from dgraph_tpu.cluster.client import ClusterClient
            self.zero = ClusterClient(zero_addrs, timeout=10.0)
            self.db.coordinator.uid_lease_fn = self.zero.assign_uids
            # one GLOBAL timestamp order across every group (ref zero
            # AssignTimestampIds): cross-group snapshot reads become
            # comparable, at one zero RPC per allocation. The ts client
            # gets a deadline WELL below the election timeout: ts
            # allocation happens under the raft lock, and a stalled
            # zero must fail the write fast, not stall heartbeats until
            # our followers depose us.
            ts_budget = max(0.05,
                            kw.get("tick_s", 0.05) *
                            kw.get("election_ticks", 10) / 3)
            self._zero_ts = ClusterClient(zero_addrs, timeout=ts_budget)
            self.db.coordinator.ts_source_fn = self._zero_ts.assign_ts
            # ALL commit decisions flow through zero's oracle in
            # multi-group mode — one global conflict window, so
            # single-group and cross-group transactions see each
            # other (ref zero/oracle.go:326: every commit is zero's)
            self.db.coordinator.commit_source_fn = self._zero_ts.commit
        # committed event stream: authoritative rebuild source
        self._events: list[tuple] = []
        # serializes execute+propose so the log's record order matches
        # the leader engine's execution order (followers must apply
        # deltas in commit-ts order)
        self._write_lock = threading.Lock()
        # serializes ordered application of decided 2PC finalizes —
        # two concurrent drains could otherwise interleave commits out
        # of ts order (see _drain_finalizes)
        self._finalize_lock = threading.Lock()
        self.node_name = f"alpha-g{self.group}-n{node_id}"
        super().__init__(node_id, raft_peers, client_addr,
                         storage=storage, learner=learner, **kw)
        if self.learner and not self._join_members:
            if self.zero is None:
                raise ValueError(
                    "--learner needs --zero to discover the group's "
                    "voters for the add_learner conf change")
            # stay quiet until the group leader conf-adds us as a
            # learner and its first append arrives
            with self.lock:
                self.node.removed = True
            threading.Thread(target=self._join_as_learner, daemon=True,
                             name=f"learn-g{self.group}-{self.id}"
                             ).start()
        elif self._join_members:
            # stay quiet (no campaigning) until the group leader adds
            # us via conf change and its first append arrives — an
            # eager candidate here would inflate terms it can't win
            with self.lock:
                self.node.removed = True
            threading.Thread(target=self._join_group, daemon=True,
                             name=f"join-g{self.group}-{self.id}").start()
            threading.Thread(target=self._report_sizes_loop,
                             daemon=True,
                             name=f"sizes-{self.id}").start()
        elif self.zero is not None:
            # explicit group: register with zero in the background so
            # its membership registry (connect decisions, /state)
            # knows this member too
            threading.Thread(target=self._register_with_zero,
                             daemon=True,
                             name=f"register-{self.id}").start()
        if self.zero is not None and not self.learner:
            # watermark beacon: leaders relay zero's global max_ts
            # through the log so idle groups' replicas can still
            # cover fresh read grants (see _watermark_loop)
            threading.Thread(target=self._watermark_loop, daemon=True,
                             name=f"wm-g{self.group}-{self.id}"
                             ).start()

    def _join_group(self):
        """Ask the group's current members to conf-change us in (ref
        conn/raft_server.go JoinCluster), retrying through elections."""
        from dgraph_tpu.cluster.client import ClusterClient
        cl = ClusterClient(self._join_members, timeout=30.0)
        try:
            my_raft = self.transport.peers.get(self.id) or \
                self.transport.addr
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and not self._stop.is_set():
                with self.lock:
                    if not self.node.removed:
                        return  # the leader reached us: we're in
                try:
                    cl.conf_change(
                        "add_learner" if self.learner else "add",
                        self.id, tuple(my_raft))
                    return
                except RuntimeError as e:
                    if "in flight" not in str(e):
                        log.warning("join_retry", node=self.id,
                                    error=str(e))
                time.sleep(0.5)
        finally:
            cl.close()

    def _register_with_zero(self):
        my_raft = self.transport.peers.get(self.id) or self.transport.addr
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not self._stop.is_set():
            got = self.zero.request({
                "op": "connect",
                "args": (f"{my_raft[0]}:{my_raft[1]}", self.group,
                         self.id, tuple(my_raft),
                         tuple(self.client_addr), 1,
                         int(bool(self.learner)))})
            if got.get("ok") and self._claim_boot_tablets():
                break
            time.sleep(1.0)
        self._report_sizes_loop()

    def _join_as_learner(self):
        """Explicit-group learner boot: register with zero (so routers
        see this replica in cluster_state), discover the group's
        voters, and ask them to conf-add us as a NON-VOTING member —
        retrying through elections until the leader's first append
        proves we are in (ref etcd AddLearnerNode)."""
        from dgraph_tpu.cluster.client import ClusterClient
        my_raft = self.transport.peers.get(self.id) or \
            self.transport.addr
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not self._stop.is_set():
            with self.lock:
                if not self.node.removed:
                    break  # the leader reached us: we're in
            try:
                self.zero.request({
                    "op": "connect",
                    "args": (f"{my_raft[0]}:{my_raft[1]}", self.group,
                             self.id, tuple(my_raft),
                             tuple(self.client_addr), 1, 1)})
                got = self.zero.request({"op": "cluster_state"})
                voters: dict[int, tuple] = {}
                if got.get("ok"):
                    for rec in got["result"]["alphas"].values():
                        if rec.get("group") == self.group \
                                and not rec.get("learner") \
                                and int(rec["id"]) != self.id:
                            nid = int(rec["id"])
                            voters[nid] = tuple(rec["client"])
                            # the learner boots knowing only its OWN
                            # raft addr: without the voters' addrs its
                            # APPEND_RESPs have nowhere to go, the
                            # leader never learns its progress, and
                            # catch-up deadlocks on the first rejected
                            # heartbeat
                            with self.lock:
                                raddr = tuple(rec["raft"])
                                self.members[nid] = raddr
                                self.transport.peers[nid] = raddr
                if voters:
                    cl = ClusterClient(voters, timeout=10.0)
                    try:
                        cl.conf_change("add_learner", self.id,
                                       tuple(my_raft))
                    except RuntimeError as e:
                        if "in flight" not in str(e):
                            log.warning("learner_join_retry",
                                        node=self.id, error=str(e))
                    finally:
                        cl.close()
            except Exception as e:  # noqa: BLE001 — keep retrying  # dglint: disable=DG07 (boot-time join loop; no request context exists yet)
                log.warning("learner_join_retry", node=self.id,
                            error=str(e))
            time.sleep(0.5)
        self._report_sizes_loop()

    def _claim_boot_tablets(self) -> bool:
        """Bulk-booted state: register every pre-loaded tablet with
        zero and push the snapshot's ts/uid watermarks so zero never
        leases below them (ref bulk/loader.go:88 zero-leased uids;
        zero.go ShouldServe claims).  False keeps the registration
        loop retrying — a silently missed watermark would let the
        first post-boot mutation lease uids that collide with bulk
        entities."""
        if not self._boot_snapshot:
            return True
        try:
            got = self.zero.request({"op": "bump_maxes", "args": (
                self.db.coordinator.max_assigned(),
                self.db.coordinator._next_uid)})
            if not got.get("ok"):
                return False
            for pred in sorted(self.db.tablets):
                if pred.startswith("dgraph."):
                    continue
                got = self.zero.request(
                    {"op": "tablet", "args": (pred, self.group)})
                if not got.get("ok"):
                    return False
                if got.get("result") != self.group:
                    log.warning("boot_tablet_conflict", pred=pred,
                                owner=got.get("result"),
                                group=self.group)
            return True
        except Exception as e:  # noqa: BLE001 — zero unreachable:  # dglint: disable=DG07 (boot-time registration loop; no request context exists yet)
            # retry from the registration loop
            log.warning("boot_claim_retry", error=str(e))
            return False

    def _report_sizes_loop(self, interval_s: float = 0.0):
        """Leader-only periodic tablet size + HEAT reports to zero —
        the rebalancer's weights (ref zero/tablet.go:180 sizes from
        membership updates). Heat = query-path touch DELTA since this
        node's last report (storage/tabstats.py `touches`); zero folds
        the deltas into a per-tablet EWMA. The first sighting of a
        tablet reports delta 0 (baseline), so a fresh leader's
        lifetime counter never lands as one giant spike."""
        if interval_s <= 0:
            # default 30s like the reference's membership updates;
            # DGRAPH_TPU_HEAT_INTERVAL_S speeds smokes/benches up
            import os as _os
            try:
                interval_s = float(_os.environ.get(
                    "DGRAPH_TPU_HEAT_INTERVAL_S", "") or 30.0)
            except ValueError:
                interval_s = 30.0
        while not self._stop.wait(interval_s):
            with self.lock:
                if self.node.role != LEADER:
                    continue
                # snapshot refs ONLY under the raft lock —
                # approx_bytes walks every posting list (O(store)) and
                # holding the lock that long would stall heartbeats
                # into an election (see the ts_budget note above)
                tabs = [(pred, tab)
                        for pred, tab in self.db.tablets.items()
                        if not pred.startswith("dgraph.")]
            live = {pred for pred, _ in tabs}
            for pred in list(self._heat_sent):
                if pred not in live:
                    # dropped/moved-away tablet: clear the baseline —
                    # a tablet moving BACK restarts touches at 0, and
                    # a stale high baseline would report delta 0
                    # through an entire query storm
                    del self._heat_sent[pred]
            batch = {}
            seen = {}
            for pred, tab in tabs:
                try:
                    nbytes = tab.approx_bytes()
                except RuntimeError:
                    continue  # mutated mid-scan; next cycle gets it
                t = int(getattr(tab, "touches", 0))
                last = self._heat_sent.get(pred)
                if last is None or t < last:
                    delta = 0  # first sight / counter restarted
                else:
                    delta = t - last
                batch[pred] = (nbytes, delta)
                seen[pred] = t
            # piggyback this node's FIRING alerts on the existing
            # report (zero's leader keeps a cluster-wide aggregation
            # for {"op":"alerts"} / dgalert --cluster): rides the
            # request dict, stripped zero-side before the propose —
            # alert state is observability, never replicated state
            from dgraph_tpu.utils import watchdog
            firing = watchdog.firing_summary()
            # ALWAYS send, even with an empty batch and no alerts:
            # the report doubles as this node's status heartbeat —
            # zero's report_silent watchdog times the gap, which is
            # the only node-down signal that still works at
            # replicas=1 (no raft peers to go silent). Zero skips
            # the raft propose for empty batches, so an idle node
            # costs one tiny RPC per interval, not log growth.
            try:
                # ONE batched request, not one RPC per tablet
                got = self.zero.request({"op": "tablet_heat",
                                         "args": (batch,),
                                         "alerts": firing,
                                         "alerts_node":
                                         self.node_name})
                if got.get("ok"):
                    # advance baselines only on a DELIVERED report: a
                    # report lost to a zero election must not eat its
                    # window's touch deltas (the EWMA would cool the
                    # hottest tablet exactly when it matters)
                    self._heat_sent.update(seen)
            except Exception:  # noqa: BLE001 — best-effort report  # dglint: disable=DG07 (daemon loop; no request context flows here)
                pass

    # -------------------------------------------------------- state machine

    def sm_apply(self, origin, rec) -> int:
        if rec == ("noop",):
            return 0  # read-barrier marker, no state change
        if isinstance(rec, tuple) and rec and rec[0] == "wm":
            # watermark beacon (leader relays zero's max_ts through
            # the log): fast-forward on EVERY replica including the
            # proposing leader — soft state only, so it's not an
            # _events record and a rebuild simply waits for the next
            # beacon. Log order makes this safe: every local commit
            # with ts <= beacon was proposed before it (the beacon is
            # proposed under _write_lock), so by the time a follower
            # applies the beacon those commits have applied here too.
            self.db.fast_forward_ts(int(rec[1]))
            return 0
        self._events.append(("rec", rec))
        if origin == (self.id, self.epoch):
            return 0  # leader pre-applied while executing the txn
        ts = self.db.apply_record(rec)
        if ts:
            self.db.fast_forward_ts(ts)
        return 0

    def sm_snapshot(self):
        from dgraph_tpu.storage.snapshot import dump_state
        snap = wire.dumps(dump_state(self.db))
        self._events = [("snap", snap)]
        return snap

    def sm_restore(self, snap: bytes):
        from dgraph_tpu.engine.db import GraphDB
        from dgraph_tpu.storage.snapshot import restore_state
        self._events = [("snap", snap)]
        db = restore_state(wire.loads_compat(snap),
                           GraphDB(**self._db_kw))
        db.coordinator.uid_lease_fn = self.db.coordinator.uid_lease_fn
        db.coordinator.ts_source_fn = self.db.coordinator.ts_source_fn
        self.db = db
        self._drop_stale_txns()

    def _rebuild_from_events(self):
        """Quorum lost mid-write: discard un-replicated local state
        (the deposed-leader-drops-uncommitted-tail analogue)."""
        from dgraph_tpu.engine.db import GraphDB
        from dgraph_tpu.storage.snapshot import restore_state
        self.epoch += 1  # own-origin records must re-apply from now on
        db = GraphDB(**self._db_kw)
        db.coordinator.uid_lease_fn = self.db.coordinator.uid_lease_fn
        db.coordinator.ts_source_fn = self.db.coordinator.ts_source_fn
        for kind, payload in self._events:
            if kind == "snap":
                db = restore_state(wire.loads_compat(payload), db)
            else:
                # apply a COPY: the rebuilt engine's tablets must not
                # alias the event-stream payloads (rollup mutates
                # tablet state in place)
                ts = db.apply_record(wire.loads(wire.dumps(payload)))
                if ts:
                    db.fast_forward_ts(ts)
        self.db = db
        self._drop_stale_txns()

    def _drop_stale_txns(self):
        """The engine object was just replaced (rebuild/snapshot
        restore): open txn handles alias the OLD engine and oracle —
        committing one against the new engine would stage against a
        dead coordinator. Drop them all; clients see 'no open txn' and
        retry, exactly the leader-change contract. Caller holds
        self.lock."""
        self._txns.clear()
        self._txn_touched.clear()

    def _evict_idle_txns(self, ttl_s: float = 300.0):
        """Abort open txns idle past the TTL (ref --abort_older_than).
        Caller holds self.lock."""
        now = time.monotonic()
        for ts, t in list(self._txn_touched.items()):
            if now - t > ttl_s:
                txn = self._txns.pop(ts, None)
                self._txn_touched.pop(ts, None)
                if txn is not None:
                    self.db.discard(txn)

    def _reconcile_pending(self, upto_ts: int | None = None,
                           evict_older_s: float | None = None) -> bool:
        """Resolve replicated cross-group stages against zero's
        decision registry (ref posting/oracle.go ProcessDelta: alphas
        learn commit decisions they missed). With upto_ts, every
        DECIDED txn whose commit could be <= upto_ts must be applied
        before a pinned read at upto_ts (undecided txns are safe: zero
        would assign them a commit_ts issued after upto_ts). With
        evict_older_s, undecided stages older than the TTL are aborted
        THROUGH zero (abort_txn records the decision, so a slow
        coordinator can't later commit what we evicted).

        Returns False when some relevant pending could NOT be verified
        or a decided one could not be applied — pinned readers must
        then fail closed (retryable) rather than serve a snapshot that
        may be missing an acknowledged commit (a parked local commit
        returns success to its client; serving around it would break
        read-your-writes)."""
        if self.zero is None:
            return True
        now = time.monotonic()
        with self.lock:
            pend = [ts for ts in self.db.pending_txns
                    if upto_ts is None or ts < upto_ts]
            # a stage inherited via raft replay/snapshot (the staging
            # leader died) starts its TTL clock at first sight here
            ages = {st: now - self._xstage_touched.setdefault(st, now)
                    for st in pend}
            if not self.db.pending_txns:
                self._xstatus_clean.clear()
        decided: list[tuple[int, int]] = []  # (commit_ts, start_ts)
        ok = True
        for st in pend:
            if upto_ts is None and evict_older_s is not None \
                    and ages[st] <= evict_older_s:
                continue  # young and nobody is waiting: no zero RPC
            if upto_ts is not None:
                with self.lock:
                    clean = self._xstatus_clean.get(st, 0)
                if clean >= upto_ts:
                    continue  # verified undecided for this snapshot

            try:
                got = self.zero.request({"op": "txn_status",
                                         "args": (st,)})
                if not got.get("ok"):
                    ok = False
                    continue
                status = got["result"]
                if not status["decided"]:
                    if upto_ts is not None:
                        with self.lock:
                            self._xstatus_clean[st] = max(
                                self._xstatus_clean.get(st, 0),
                                upto_ts)
                    if evict_older_s is None or \
                            ages[st] <= evict_older_s:
                        continue
                    if st < status.get("floor", 0):
                        # zero trimmed this ts range: the decision is
                        # unknowable, and recording an abort could
                        # contradict a commit another group applied.
                        # Keep the stage pending (operator-visible)
                        # rather than guess.
                        ok = False
                        continue
                    final = self.zero.request(
                        {"op": "abort_txn", "args": (st,)})
                    if not final.get("ok"):
                        ok = False
                        continue
                    status = {"commit_ts": final["result"]}
                decided.append((int(status["commit_ts"]), st))
            except RequestAborted:
                # a cancelled/expired caller must not be absorbed
                # into "retry next pass"
                raise
            except Exception:  # noqa: BLE001 — next pass retries
                ok = False
                continue
        if decided and not self._drain_finalizes():
            ok = False
        return ok

    def _drain_finalizes(self, hint: tuple[int, int] | None = None
                         ) -> bool:
        """Public entry: takes the write lock first (global lock order
        is _write_lock -> _finalize_lock -> lock; the local-commit path
        drains while already holding _write_lock, so acquiring
        _finalize_lock before _write_lock anywhere would invert)."""
        with self._write_lock:
            return self._drain_finalizes_locked(hint)

    def _drain_finalizes_locked(self, hint: tuple[int, int] | None = None
                                ) -> bool:
        """Apply every DECIDED pending 2PC fragment in COMMIT-TS
        order, atomically with respect to other drains. Caller holds
        _write_lock.

        Racing coordinators' finalize RPCs (or a reconcile racing one)
        can otherwise deliver commits out of ts order; an out-of-order
        overlay delta both mis-serializes single-value overwrite
        expansion and breaks every ts-sorted overlay consumer — the
        split-bank chaos run lost a committed credit to exactly this
        (a later-committed transfer's read missed it, then overwrote).

        The status GATHER happens under the same lock as the apply
        loop: a drain that only knew about a later commit could
        otherwise apply it while an earlier-decided stage (whose
        status fetch failed elsewhere) is still pending.  If ANY
        pending stage's status cannot be fetched, the whole drain
        aborts — applying around an unknown would gamble on its order.
        Ordering is sufficient: zero decides serially, so a stage
        still undecided during the gather will get a commit_ts above
        everything already decided.  `hint` = (commit_ts, start_ts)
        already known by the caller (saves one RPC)."""
        with self._finalize_lock:
            with self.lock:
                pend = sorted(self.db.pending_txns)
            decided: list[tuple[int, int]] = []
            for st in pend:
                if hint is not None and st == hint[1]:
                    decided.append((int(hint[0]), st))
                    continue
                try:
                    got = self.zero.request({"op": "txn_status",
                                             "args": (st,)})
                except RequestAborted:
                    raise
                except Exception:  # noqa: BLE001
                    return False
                if not got.get("ok"):
                    return False
                if got["result"]["decided"]:
                    decided.append(
                        (int(got["result"]["commit_ts"]), st))
            for c, st in sorted(decided):
                try:
                    # chaos seam: delay/fail a decided fragment's
                    # finalize apply — a FailpointError is swallowed
                    # below like any transient failure (the reconcile
                    # machinery retries next pass, which is exactly
                    # the recovery path under test). An armed sleep
                    # stalling the drain under _finalize_lock is the
                    # POINT of the seam: finalize ordering pressure is
                    # what the nemesis schedules exist to create.
                    failpoint.fire("txn.xfinalize")  # dglint: disable=DG04 (chaos seam: the armed delay must stall this drain; inert cost is one dict check)
                    self._replicate_record_locked(("xfinalize", st, c))
                except RequestAborted:
                    raise
                except Exception:  # noqa: BLE001 — retried next pass
                    return False
                with self.lock:
                    self._xstage_touched.pop(st, None)
                    self._xstatus_clean.pop(st, None)
            return True

    def _drop_txn_handle(self, txn) -> None:
        """Forget (and, if still open, abort) a leader-local txn
        handle — the oracle must never keep a start_ts pinned for a
        txn its client cannot reach anymore."""
        with self.lock:
            self._txns.pop(txn.start_ts, None)
            self._txn_touched.pop(txn.start_ts, None)
            if not txn.done:
                self.db.discard(txn)

    def _drain_before_local_apply(self, commit_ts: int) -> bool:
        """Between a local commit's ts RESERVATION and its APPLY, land
        every already-decided pending 2PC fragment (all necessarily
        below our ts). Caller holds _write_lock.

        Retries patiently on gather failure: zero answered the
        reservation RPC moments ago, so unreachability here is a
        transient blip — and applying around an unknown-order pending
        risks the out-of-order hard error at apply
        (storage/tablet.py Tablet.apply) when that fragment finalizes.
        Returns False on sustained failure; the caller must then PARK
        the reserved commit as a pending fragment instead of applying
        (applying anyway would deadlock the group: the lower-ts
        fragment could never apply NOR fold past the local delta)."""
        with self.lock:
            if not self.db.pending_txns:
                return True
        deadline = time.monotonic() + 30.0
        while not self._drain_finalizes_locked():
            if time.monotonic() >= deadline or self._stop.is_set():
                log.warning("commit_undrained_pendings",
                            commit_ts=commit_ts)
                return False
            time.sleep(0.05)
        return True

    def _applied_watermark(self) -> int:
        """Highest commit timestamp this replica has applied (the
        coordinator's max_assigned is fast-forwarded by every applied
        record, so on a follower/learner it IS the applied watermark).
        Caller holds self.lock."""
        return self.db.coordinator.max_assigned()

    def _await_watermark(self, read_ts: int, ctx=None,
                         wait_s: float = 2.0):
        """Watermark-bounded follower read, the wait half: block until
        this replica's applied watermark covers `read_ts`, bounded by
        `wait_s` (and half the caller's remaining deadline, so the
        typed retry still reaches it). On timeout raise the typed
        StaleRead — the router retries on another replica rather than
        ever serving a snapshot older than the granted timestamp."""
        if ctx is not None:
            rem = ctx.remaining_ms()
            if rem is not None:
                wait_s = min(wait_s, max(0.0, rem / 1000.0) / 2)
        with self.lock:
            deadline = time.monotonic() + wait_s
            while True:
                wm = self._applied_watermark()
                if wm >= read_ts:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    metrics.inc_counter("dgraph_stale_reads_total")
                    raise StaleRead(read_ts, wm)
                # capped wait: the watermark can advance without an
                # applied_cv notify (leader-local allocations)
                self.applied_cv.wait(min(remaining, 0.05))

    def _watermark_loop(self, interval_s: float = 0.0):
        """Leader-only watermark beacon (ref zero's MaxAssigned in the
        oracle delta stream): zero's max_ts is GLOBAL, so a group
        whose last local commit predates another group's can never
        cover a fresh read grant on its own — every watermark-bounded
        read there would burn its full wait and fail over. The leader
        periodically reads zero's current max_ts (the non-bumping
        read_ts op) and, when it is ahead of the local watermark,
        replicates it as a ("wm", ts) record so every replica —
        learners included — fast-forwards.

        Safety: proposed under _write_lock, so any LOCAL commit with
        ts <= beacon is already in the log ahead of it; cross-group
        stages decided-but-unfinalized are skipped here (pending_txns
        gate) and independently blocked at read time by the
        pending-txn check in the follower-read path."""
        if interval_s <= 0:
            import os as _os
            try:
                interval_s = float(_os.environ.get(
                    "DGRAPH_TPU_WM_INTERVAL_S", "") or 0.2)
            except ValueError:
                interval_s = 0.2
        while not self._stop.wait(interval_s):
            with self.lock:
                if self.node.role != LEADER:
                    continue
                wm = self._applied_watermark()
            try:
                got = self.zero.request({"op": "read_ts"})
                if not got.get("ok"):
                    continue
                t = int(got["result"])
            except Exception:  # noqa: BLE001 — zero blip: next tick  # dglint: disable=DG07 (daemon loop; no request context flows here)
                continue
            if t <= wm:
                continue  # idle or already covered: no log traffic
            with self._write_lock:
                with self.lock:
                    # _write_lock freezes pending_txns (stages and
                    # local commits both mutate it under that lock),
                    # so these checks stay true through the propose
                    skip = (self.node.role != LEADER
                            or self._stop.is_set()
                            or bool(self.db.pending_txns)
                            or t <= self._applied_watermark())
                if skip:
                    continue
                try:
                    # outside self.lock like _replicate_record_locked:
                    # propose_and_wait sends + waits on applied_cv
                    self.propose_and_wait(("wm", t))
                except Exception:  # noqa: BLE001 — quorum blip  # dglint: disable=DG07 (daemon loop; no request context flows here)
                    continue

    def _read_barrier(self):
        """Linearizable-read barrier for pinned reads (raft §8): a
        freshly elected leader may hold committed-but-unapplied entries
        from the previous term, and cannot even KNOW the old commit
        index until an entry of its own term commits. Committing one
        no-op round-trip guarantees everything acknowledged before this
        read is applied here."""
        with self.lock:
            if self.node.role != LEADER:
                raise NotLeader(self.node.leader_id)
        # ALWAYS a quorum round-trip: a partitioned ex-leader that
        # still believes it leads cannot commit this no-op, so it
        # fails here instead of serving a stale snapshot (read-index
        # semantics; a local caught-up check is not enough)
        ok, _ = self.propose_and_wait(("noop",))
        if not ok:
            raise RuntimeError("read barrier failed (no quorum)")

    # --------------------------------------------------------------- writes

    def _check_ownership(self, preds, subjects=None):
        """Multi-group mode: every touched predicate must be served by
        THIS group per Zero's map; unclaimed predicates are claimed,
        FENCED tablets (the move's short `fenced` phase — reads never
        fence) reject writes retryably (ref zero.go ShouldServe +
        oracle's tablet checks at commit). A predicate owned elsewhere
        raises the TYPED TabletMisrouted so a router holding a
        pre-flip map refreshes and re-routes instead of surfacing 500.

        `subjects` — (pred, subject_uid) pairs of the write — lets a
        hash-range SPLIT predicate verify per-row ownership: each
        subject must hash into a shard this group serves
        (cluster/shard.py). Without subjects a split predicate rejects
        the write outright: only the router's per-shard 2PC path
        carries resolved uids. Caller holds _write_lock, so a
        concurrent export (which also takes it) serializes against
        in-flight writes."""
        if self.zero is None:
            return
        tmap = self.zero.request({"op": "tablet_map"})
        if not tmap.get("ok"):
            raise RuntimeError("zero unreachable; cannot verify "
                               "tablet ownership")
        if tmap["result"].get("fence"):
            # cluster-wide client-write fence (async replication):
            # this cluster is a standby — or the fenced old primary
            # after a promotion. Replication applies never come here
            # (move_apply/repl_install replicate records directly).
            raise WriteFenced(tmap["result"].get("repl_phase", ""))
        tablets = tmap["result"]["tablets"]
        moving = tmap["result"]["moving"]
        splits = tmap["result"].get("splits", {})
        subs_by_pred: dict[str, list[int]] = {}
        for p, u in subjects or ():
            subs_by_pred.setdefault(p, []).append(int(u))
        for p in preds:
            if p == "*" or p.startswith("dgraph."):
                continue
            if p in moving:
                raise RuntimeError(
                    f"tablet {p!r} is being moved; retry shortly")
            if p in splits:
                from dgraph_tpu.cluster.shard import owner_for_uid
                subs = subs_by_pred.get(p)
                if subs is None:
                    raise TabletMisrouted(
                        p, None,
                        f"tablet {p!r} is split across groups; route "
                        "writes per subject through the cluster router")
                for u in subs:
                    owner = owner_for_uid(splits[p], u)
                    if owner != self.group:
                        raise TabletMisrouted(
                            p, owner,
                            f"subject {u:#x} of split tablet {p!r} "
                            f"belongs to group {owner}; refresh the "
                            "tablet map and re-route")
                continue
            owner = tablets.get(p)
            if owner is None:
                got = self.zero.tablet(p, self.group)
                if got != self.group:
                    raise TabletMisrouted(
                        p, got if got > 0 else None,
                        f"tablet {p!r} belongs to group {got}; "
                        "refresh the tablet map and re-route")
            elif owner != self.group:
                raise TabletMisrouted(
                    p, owner,
                    f"tablet {p!r} belongs to group {owner}; "
                    "refresh the tablet map and re-route")

    def _capture_and_replicate(self, fn) -> Any:
        """Run `fn(db)` on the leader with the record sink attached,
        then replicate every captured record; quorum loss rolls the
        engine back from the committed event stream. Caller holds
        _write_lock."""
        with self.lock:
            if self.node.role != LEADER:
                raise NotLeader(self.node.leader_id)
            captured: list = []
            prev = self.db.on_record
            self.db.on_record = captured.append
            try:
                result = fn(self.db)
            finally:
                self.db.on_record = prev
        for rec in captured:
            ok, _ = self.propose_and_wait(rec)
            if not ok:
                with self.lock:
                    self._rebuild_from_events()
                raise RuntimeError(
                    "write not replicated (no quorum)")
        return result

    def _replicate_write(self, fn, preds=()) -> Any:
        with self._write_lock:
            self._check_ownership(preds)
            return self._capture_and_replicate(fn)

    @staticmethod
    def _mutation_preds(kw: dict) -> set:
        from dgraph_tpu.server.acl import nquad_predicates
        preds = set(nquad_predicates(
            kw.get("set_nquads", ""), kw.get("del_nquads", ""),
            kw.get("set_json"), kw.get("delete_json")))
        return {p.lstrip("~") for p in preds}

    def _replicate_record(self, rec) -> None:
        """Apply a pre-built engine record on the leader and replicate
        it (tablet import/drop — records that don't come from a txn
        sink). The leader applies a deep COPY: the log/_events keep the
        original payload, and later in-place tablet mutations (rollup
        folds) must never rewrite replicated history."""
        with self._write_lock:
            self._replicate_record_locked(rec)

    def _replicate_record_locked(self, rec) -> None:
        """_replicate_record body for callers already holding
        _write_lock (the finalize drain, which also runs from the
        local-commit path under the commit's own _write_lock)."""
        with self.lock:
            if self.node.role != LEADER:
                raise NotLeader(self.node.leader_id)
            ts = self.db.apply_record(wire.loads(wire.dumps(rec)))
            if ts:
                self.db.fast_forward_ts(ts)
        ok, _ = self.propose_and_wait(rec)
        if not ok:
            with self.lock:
                self._rebuild_from_events()
            raise RuntimeError("record not replicated (no quorum)")

    @staticmethod
    def _req_ctx(req: dict):
        """RequestContext a coordinator propagated on the wire
        (deadline_ms = its remaining budget): this worker inherits the
        budget widened by a small skew allowance, so the coordinator
        times out first and the worker's abort is the backstop (ref
        worker RPCs inheriting the query context)."""
        ms = req.get("deadline_ms")
        tenant = str(req.get("tenant") or "")
        if ms is None:
            if req.get("trace_id") or tenant:
                # no deadline, but the caller IS tracing (or carries a
                # tenant tag for reqlog/QoS attribution): keep the
                # context joined through the engine's bind_request
                return RequestContext.background(
                    trace_id=req.get("trace_id", ""),
                    parent_span=req.get("parent_span", ""),
                    tenant=tenant)
            return None
        return RequestContext.from_deadline_ms(
            ms, trace_id=req.get("trace_id", ""),
            skew_s=PROPAGATION_SKEW_S,
            parent_span=req.get("parent_span", ""),
            tenant=tenant)

    def _run_task(self, req: dict, read_ts: int):
        """Dispatch one federated task kind against the local tablet.
        Caller holds _write_lock + lock with leadership verified."""
        kind = req["kind"]
        if kind == "schema_state":
            return self.db.schema.describe_all()
        tab = self.db.tablets.get(req["pred"])
        if tab is None:
            return None
        uids = req.get("uids")
        rev = bool(req.get("reverse"))
        if kind == "edges":
            get = tab.get_reverse_uids if rev else tab.get_dst_uids
            return [get(int(u), read_ts) for u in uids.tolist()]
        if kind == "postings":
            return [tab.get_postings(int(u), read_ts)
                    for u in uids.tolist()]
        if kind == "expand":
            return tab.expand_frontier(uids, read_ts, rev)
        if kind == "src_uids":
            return tab.src_uids(read_ts)
        if kind == "dst_uids":
            return tab.dst_uids(read_ts)
        if kind == "index":
            return [tab.index_uids(bytes(t), read_ts)
                    for t in req["tokens"]]
        if kind == "counts":
            if rev:
                return [len(tab.get_reverse_uids(int(u), read_ts))
                        for u in uids.tolist()]
            return [tab.count_of(int(u), read_ts)
                    for u in uids.tolist()]
        if kind == "count_table":
            # the proxy's dirty() is False (the overlay never leaves
            # this group), so this table must be MVCC-exact at read_ts
            # — not the base-only fast table the local path splits
            # against its own overlay
            import numpy as _np
            srcs = tab.src_uids(read_ts)
            cnts = _np.asarray(
                [tab.count_of(int(u), read_ts) for u in srcs.tolist()],
                _np.int64)
            return (srcs, cnts)
        if kind == "facets":
            return [tab.get_facets(int(s), int(d), read_ts)
                    for s, d in req["pairs"]]
        if kind == "sort_key_pairs":
            return tab.sort_key_pairs()
        raise ValueError(f"unknown task kind {kind!r}")

    # ----------------------------------------------------------------- RPC

    # work-bearing ops that consume engine/leader time — including
    # cross-group 2PC STAGING (a shed xstage is safe: the coordinator
    # aborts at zero and clears staged fragments, topology.py
    # _mutate_multigroup). admin, stats and xfinalize are never shed:
    # finalize carries an already-DECIDED transaction, and shedding it
    # would stall that decision behind the very overload it relieves
    _ADMITTED_OPS = ("query", "mutate", "task", "xstage")

    def handle_request(self, req: dict) -> dict:
        if req.get("op") in self._ADMITTED_OPS:
            self._admit_tenant(req)
        if not self.max_pending \
                or req.get("op") not in self._ADMITTED_OPS:
            return self._handle_admitted(req)
        from dgraph_tpu.utils import metrics
        with self._admission:
            if self._inflight >= self.max_pending:
                metrics.inc_counter("dgraph_queries_shed_total")
                raise Overloaded(
                    f"node {self.node_name} is overloaded: "
                    f"{self._inflight} requests in flight "
                    f"(max_pending={self.max_pending}); retry with "
                    "jittered backoff")
            self._inflight += 1
            metrics.set_gauge("dgraph_pending_queries", self._inflight)
        try:
            return self._handle_admitted(req)
        finally:
            with self._admission:
                self._inflight -= 1
                metrics.set_gauge("dgraph_pending_queries",
                                  self._inflight)

    def _admit_tenant(self, req: dict) -> None:
        """Per-tenant token-bucket admission, layered UNDER the shared
        max_pending plane: a tenant that exhausts its own budget sheds
        TYPED (Overloaded -> the caller's 429 class) while other
        tenants keep their full rate. Commits/finalizes are never
        shed here — they ride ops outside _ADMITTED_OPS."""
        qos = getattr(self, "qos", None)  # absent on bare test shells
        if qos is None:
            return
        tenant = str(req.get("tenant") or "default")
        if qos.admit(tenant):
            return
        from dgraph_tpu.utils import metrics
        metrics.inc_counter("dgraph_tenant_shed_total",
                            labels={"tenant": tenant})
        raise Overloaded(
            f"tenant {tenant!r} exceeded its admission rate on "
            f"{self.node_name}; retry with jittered backoff")

    def _misroute_guard_query(self, q: str, variables) -> None:
        """A query naming a tablet this group MOVED AWAY must fail
        TYPED (TabletMisrouted), never silently return empty rows —
        the read-parity hazard of a client racing a cutover with a
        stale routing map. Zero-cost until this node has actually
        moved a tablet out (moved_out empty); a malformed query falls
        through to the engine's own parser error.

        Predicates reached only via expand() never appear in the
        query text or in query_predicates, so this screen cannot see
        them; that half of the window is closed by the executor-level
        ownership hook at expansion time
        (query/executor.py Executor._expand_ownership_guard), which
        raises the same typed TabletMisrouted when expand()
        materializes a moved or split predicate."""
        if self.zero is None or (not self.db.moved_out
                                 and not self.db.split_partial):
            return
        suspects = set(self.db.moved_out) | self.db.split_partial
        if not any(p in q for p in suspects):
            # a referenced predicate appears literally in the query
            # text, so the substring screen keeps the guard O(names)
            # on the hot path instead of re-parsing every query
            # forever once any tablet has ever moved away
            return
        try:
            from dgraph_tpu.gql import parse
            from dgraph_tpu.server.acl import query_predicates
            preds = {p.lstrip("~")
                     for p in query_predicates(parse(q, variables))}
        except Exception:  # noqa: BLE001 — the engine owns the error  # dglint: disable=DG07 (parse errors surface identically from db.query below)
            return
        for p in preds:
            if p in self.db.moved_out and p not in self.db.tablets:
                raise TabletMisrouted(p, self.db.moved_out[p])
            if p in self.db.split_partial:
                # this member holds only a hash range: a whole-
                # predicate read here would be silently partial —
                # the router re-fetches the map and federates
                raise TabletMisrouted(
                    p, None,
                    f"tablet {p!r} is split across groups; refresh "
                    "the tablet map and fan out per sub-tablet")

    def _handle_admitted(self, req: dict) -> dict:
        conf = self.handle_conf_request(req)
        if conf is not None:
            return conf
        op = req.get("op")
        if op == "query":
            self._misroute_guard_query(req["q"], req.get("vars"))
            # any replica serves best-effort snapshot reads
            # (edgraph/server.go:760); under the lock because the
            # apply/restore threads mutate and rebind self.db.
            # read_ts (a zero-issued GLOBAL timestamp) pins the MVCC
            # snapshot for cross-group scatter reads — leader-only,
            # since the leader applies its commits synchronously so a
            # read at T sees exactly the commits with ts <= T.
            read_ts = int(req.get("read_ts", 0)) or None
            ctx = self._req_ctx(req)
            if read_ts is not None and req.get("be"):
                # watermark-bounded follower read (ANY replica,
                # learners included): pinned at a zero-granted
                # read_ts, served only once the local applied
                # watermark covers it — a lagging replica degrades to
                # a typed retry-elsewhere, never to a snapshot older
                # than the granted timestamp. No quorum barrier: the
                # watermark wait plays its role for a ts that was
                # granted BEFORE the read (raft applies records in
                # commit-ts order, so watermark >= read_ts means every
                # commit <= read_ts has applied here).
                self._await_watermark(read_ts, ctx)
                with self.lock:
                    if any(ts < read_ts
                           for ts in self.db.pending_txns):
                        # a decided-but-unfinalized 2PC fragment could
                        # hold a commit <= read_ts; only the leader's
                        # reconcile path can verify — fail over
                        raise StaleRead(read_ts,
                                        self._applied_watermark())
                    out = self.db.query(
                        req["q"], variables=req.get("vars"),
                        read_ts=read_ts, ctx=ctx)
                return {"ok": True, "result": out}
            if read_ts is not None:
                # pinned read: pay the quorum barrier FIRST — a deposed
                # leader cannot commit the no-op, so it can never serve
                # a stale pinned snapshot. The barrier runs OUTSIDE
                # _write_lock (it is a full network round-trip; holding
                # the lock across it would serialize every write behind
                # each pinned read). Then take _write_lock only around
                # the local query so no commit is mid-flight (applied
                # locally, not yet quorum-acked — reading that state
                # would be a dirty read if replication later rolls
                # back). A write that sneaks in between barrier and
                # lock is fully replicated by the time we read — still
                # a consistent snapshot at read_ts.
                self._read_barrier()
                # AFTER the barrier (so a just-elected leader has
                # applied its inherited log first): decided-but-
                # unapplied cross-group commits <= read_ts must land
                # before this snapshot is served; fail CLOSED when a
                # pending cannot be verified (it may hold a commit
                # already acknowledged to its client)
                if not self._reconcile_pending(upto_ts=read_ts):
                    raise RuntimeError(
                        "cannot verify pending transactions against "
                        "the decision registry; retry")
                with self._write_lock:
                    with self.lock:
                        if self.node.role != LEADER:
                            raise NotLeader(self.node.leader_id)
                        out = self.db.query(
                            req["q"], variables=req.get("vars"),
                            read_ts=read_ts, ctx=ctx)
                return {"ok": True, "result": out}
            with self.lock:
                out = self.db.query(req["q"], variables=req.get("vars"),
                                    ctx=ctx)
            return {"ok": True, "result": out}
        if op == "mutate":
            kw = dict(req["kw"])
            commit_now = kw.pop("commit_now", True)
            start_ts = kw.pop("start_ts", 0)
            # a coordinator-propagated deadline bounds the stage too,
            # not just reads — an expired client must not keep this
            # group's leader staging on its behalf
            ctx = self._req_ctx(req)
            preds = self._mutation_preds(kw) if self.zero else ()
            # commit-now mutations take the SAME stage-then-commit flow
            # as interactive txns: the commit handler drains decided
            # lower-ts 2PC fragments between ts reservation and apply,
            # so a commit-now write can never overtake a pending
            # cross-group finalize (ref worker/draft.go:435 — one Raft
            # log gives the reference this ordering for free)
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                self._evict_idle_txns()
                if start_ts:
                    txn = self._txns.get(start_ts)
                    if txn is None:
                        raise KeyError(
                            f"no open txn at startTs={start_ts} "
                            "(leader changed?)")
                else:
                    txn = self.db.new_txn()
            if kw.get("query") and self.zero is not None:
                # an upsert stage READS at the txn's start_ts: pay the
                # same linearizable-read protocol as a pinned query
                # (barrier: a fresh leader applies inherited xstage
                # records first; reconcile: decided-but-unapplied
                # fragments <= start_ts land before the read) — or the
                # read-modify-write computes against a snapshot missing
                # a commit it logically follows and overwrites it (the
                # mixed commit-now/2PC bank run lost exactly such a
                # credit)
                try:
                    self._read_barrier()
                    if not self._reconcile_pending(
                            upto_ts=txn.start_ts):
                        raise RuntimeError(
                            "cannot verify pending transactions "
                            "against the decision registry; retry")
                except Exception:
                    if not start_ts:
                        self.db.discard(txn)
                    raise
            with self._write_lock:
                try:
                    self._check_ownership(preds)
                except Exception:
                    # a txn created HERE must not leak its start_ts in
                    # the oracle (a pinned _active entry freezes the
                    # rollup watermark forever); an existing open txn
                    # stays open — the client may retry after the move
                    if not start_ts:
                        with self.lock:
                            self.db.discard(txn)
                    raise
                with self.lock:
                    if self.node.role != LEADER:
                        if not start_ts:
                            self.db.discard(txn)
                        raise NotLeader(self.node.leader_id)
                    try:
                        out = self.db.mutate(txn, commit_now=False,
                                             ctx=ctx, **kw)
                    except Exception:
                        # a failed stage aborts the whole txn (fail
                        # fast, like the reference's aborted TxnContext)
                        self._txns.pop(txn.start_ts, None)
                        self._txn_touched.pop(txn.start_ts, None)
                        self.db.discard(txn)
                        raise
                    self._txns[txn.start_ts] = txn
                    self._txn_touched[txn.start_ts] = time.monotonic()
                    out.setdefault("extensions", {})["txn"] = {
                        "start_ts": txn.start_ts}
            if commit_now:
                try:
                    resp = self.handle_request(
                        {"op": "commit",
                         "params": {"startTs": str(txn.start_ts)}})
                except Exception:
                    self._drop_txn_handle(txn)
                    raise
                if not resp.get("ok"):
                    # the client of a commit-now mutation has no txn
                    # handle to retry or abort with: a failed nested
                    # commit must not leave the staged txn registered
                    # (it would pin the fold watermark until the TTL)
                    self._drop_txn_handle(txn)
                    return resp
                # keep the stage's payload (uids map for blank nodes,
                # like a dgo CommitNow mutation) and graft the commit
                # extensions onto it
                out.setdefault("extensions", {}).update(
                    resp["result"].get("extensions", {}))
                return {"ok": True, "result": out}
            return {"ok": True, "result": out}
        if op == "commit":
            params = req.get("params", {})
            start_ts = int(params.get("startTs", 0))
            abort = params.get("abort", "false") == "true"
            with self._write_lock:
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    txn = self._txns.get(start_ts)
                if txn is None:
                    raise KeyError(
                        f"no open txn at startTs={start_ts}")
                if abort:
                    with self.lock:
                        self._txns.pop(start_ts, None)
                        self._txn_touched.pop(start_ts, None)
                        self.db.discard(txn)
                    return {"ok": True, "result": {
                        "extensions": {"txn": {"start_ts": start_ts,
                                               "aborted": True}}}}
                # a tablet may have MOVED since the stage: committing
                # here would write to a group that no longer owns it.
                # Checked BEFORE removing the handle — on failure the
                # txn stays open (and its oracle entry alive) so the
                # advertised retry actually works
                self._check_ownership(
                    {pred for pred, _ in txn.staged},
                    subjects=[(p, op.src) for p, op in txn.staged])
                with self.lock:
                    self._txns.pop(start_ts, None)
                    self._txn_touched.pop(start_ts, None)
                try:
                    commit_ts = self.db.commit_reserve(txn)
                except Exception:
                    # reservation failure (conflict abort, zero ts RPC
                    # down) must release start_ts in the oracle
                    if not txn.done:
                        self.db.discard(txn)
                    raise
                # Every already-DECIDED cross-group fragment carries a
                # commit ts BELOW ours (zero assigns monotonically and
                # decides serially), so applying them first reproduces
                # log order; anything still undecided will land above
                # ours and may apply later
                if self._drain_before_local_apply(commit_ts):
                    commit_ts = self._capture_and_replicate(
                        lambda db: db.commit_apply(txn, commit_ts))
                else:
                    # zero went dark mid-commit with a pending whose
                    # order is unknowable. The decision IS recorded at
                    # zero, so park this commit as a pending fragment:
                    # the reconcile machinery applies everything in ts
                    # order once zero answers — the same guarantee a
                    # 2PC participant gives when a finalize delivery
                    # fails (topology.py relies on it already)
                    schemas = {
                        p: self.db.schema.get_or_default(p).describe()
                        for p in {pred for pred, _ in txn.staged}}
                    self._replicate_record_locked(
                        ("xstage", txn.start_ts, list(txn.staged),
                         schemas,
                         sorted(int(k) for k in txn.conflict_keys)))
                    with self.lock:
                        self._xstage_touched[txn.start_ts] = \
                            time.monotonic()
            return {"ok": True, "result": {
                "extensions": {"txn": {"start_ts": start_ts,
                                       "commit_ts": commit_ts}}}}
        if op == "task":
            # one attr-level task of a federated query (ref
            # worker/task.go:131 ProcessTaskOverNetwork landing on the
            # serving group): leader-only snapshot read at a global
            # read_ts. The first task of a query pays the quorum read
            # barrier; every task reconciles decided cross-group
            # commits <= read_ts first.
            read_ts = int(req.get("read_ts", 0))
            pred = req.get("pred")
            if pred and pred in self.db.moved_out \
                    and pred not in self.db.tablets:
                # stale-routed federated task after a cutover: typed,
                # so the coordinator re-fetches the map and re-fans
                raise TabletMisrouted(pred, self.db.moved_out[pred])
            if pred and req.get("whole") \
                    and pred in self.db.split_partial:
                # a coordinator whose map predates a split flip asks
                # for the WHOLE predicate here, but this group holds
                # only a hash range — answering would be silently
                # partial. (SplitRemoteTablet's per-shard fan-out
                # sends whole=False and is served normally.)
                raise TabletMisrouted(
                    pred, None,
                    f"tablet {pred!r} is split across groups; refresh "
                    "the tablet map and fan out per sub-tablet")
            # the coordinator's propagated budget: give up BEFORE the
            # quorum barrier (its round-trip is the expensive part)
            # and again before reading — a coordinator that already
            # timed out must not keep consuming this group's leader
            ctx = self._req_ctx(req)
            if ctx is not None:
                ctx.check("task")
            # EVERY task pays the quorum barrier: the client's leader
            # can change mid-query, and a once-per-query (or cached
            # per-term) barrier would let a fresh or partitioned
            # ex-leader serve committed-but-unapplied state. Barrier
            # first, then reconcile decided cross-group commits.
            self._read_barrier()
            if not self._reconcile_pending(upto_ts=read_ts):
                raise RuntimeError(
                    "cannot verify pending transactions against "
                    "the decision registry; retry")
            with self._write_lock:
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    if ctx is not None:
                        ctx.check("task read")
                    return {"ok": True,
                            "result": self._run_task(req, read_ts)}
        if op == "xstage":
            # one group's fragment of a cross-group transaction,
            # replicated at stage time so the 2PC stage survives
            # leader changes (ref worker/mutation.go:432 proposeOrSend)
            from dgraph_tpu.gql.nquad import nquad_from_wire
            start_ts = int(req["start_ts"])
            # chaos seam: delay/fail a group's 2PC stage — the
            # coordinator-dies-mid-stage and slow-participant nemeses
            # (an armed error surfaces to the coordinator, which
            # aborts at zero and clears staged fragments)
            failpoint.fire("txn.xstage")
            nqs = [(nquad_from_wire(t), bool(d)) for t, d in req["nqs"]]
            preds = {nq.predicate for nq, _ in nqs}
            subjects = []
            for nq, _ in nqs:
                try:  # split-tablet row routing needs resolved uids;
                    # blanks fail xstage_ops with its own error below
                    subjects.append((nq.predicate, int(nq.subject, 0)))
                except ValueError:
                    pass
            with self._write_lock:
                self._check_ownership(preds, subjects=subjects)
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    staged, keys, schemas = self.db.xstage_ops(
                        start_ts, nqs)
            self._replicate_record(
                ("xstage", start_ts, staged, schemas,
                 sorted(int(k) for k in keys)))
            with self.lock:
                self._xstage_touched[start_ts] = time.monotonic()
            # stale stages (coordinator died) reconcile via zero's
            # decision registry on the same TTL as idle txns
            self._reconcile_pending(evict_older_s=300.0)
            return {"ok": True,
                    "result": {"keys": sorted(int(k) for k in keys)}}
        if op == "xfinalize":
            start_ts = int(req["start_ts"])
            commit_ts = int(req["commit_ts"])
            with self.lock:
                known = start_ts in self.db.pending_txns
            if known:
                self._drain_finalizes(hint=(commit_ts, start_ts))
            return {"ok": True, "result": {"applied": known}}
        if op == "alter":
            ctx = self._req_ctx(req)
            self._replicate_write(
                lambda db: db.alter(ctx=ctx, **req["kw"]))
            return {"ok": True, "result": {}}
        if op == "status":
            from dgraph_tpu.utils import metrics
            with self.lock:
                lag = max(0, self.node.commit_index
                          - self.node.applied_index)
                if self.node.learner:
                    metrics.set_gauge("dgraph_learner_lag", lag)
                return {"ok": True, "result": {
                    "id": self.id, "group": self.group,
                    "role": self.node.role,
                    "leader": self.node.leader_id,
                    "term": self.node.term,
                    "applied": self.node.applied_index,
                    "learner": self.node.learner,
                    "lag": lag,
                    "watermark": self._applied_watermark(),
                    "tablets": sorted(self.db.tablets),
                    "pending": sorted(self.db.pending_txns),
                    "max_ts": self.db.coordinator.max_assigned()}}
        if op == "stats":
            # the wire analogue of HTTP /debug/stats (same payload,
            # histograms included), bundled with the request log and
            # counter snapshot so one poll carries a node's whole
            # observability surface over the cluster wire alone
            # (tools/dgtop.py itself polls the HTTP endpoints)
            from dgraph_tpu.utils import metrics, reqlog, watchdog
            # self.lock only pins the db BINDING (restore rebinds it);
            # the stats walk itself runs unlocked — a cold cache
            # recomputes O(postings) aggregates, and holding the Raft
            # state lock for that would stall apply/commit into
            # election timeouts. debug_stats retries/degrades on
            # concurrent-apply races: a skewed count is fine, a
            # stalled quorum is not.
            with self.lock:
                db = self.db
            stats = db.debug_stats()
            stats["node"] = self.node_name
            stats["group"] = self.group
            stats["requests"] = reqlog.snapshot()
            stats["netfault"] = netfault.rules()
            stats["lastHeard"] = self.peer_ages()
            stats["alerts"] = watchdog.firing_summary()
            with self.lock:
                stats["learner"] = self.node.learner
                stats["learnerLag"] = max(
                    0, self.node.commit_index
                    - self.node.applied_index)
                if self.node.learner:
                    metrics.set_gauge("dgraph_learner_lag",
                                      stats["learnerLag"])
            metrics.collect_process_gauges()
            stats["counters"] = metrics.counters_snapshot()
            stats["gauges"] = metrics.gauges_snapshot()
            stats["histograms"] = metrics.histograms_snapshot()
            return {"ok": True, "result": stats}
        if op == "export_tablet":
            # tablet move, source side (worker/predicate_move.go:81).
            # _write_lock serializes against in-flight writes: anything
            # committed before the export is in the blob; anything
            # after re-checks Zero's map and sees the moving mark.
            with self._write_lock:
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    pred = req["pred"]
                    if pred not in self.db.tablets:
                        return {"ok": False, "error":
                                f"tablet {pred!r} not served here"}
                    blob = wire.dumps(self.db.export_tablet(pred))
            return {"ok": True, "result": blob}
        if op == "import_tablet":
            # destination side: replicate the whole tablet as one
            # record so every group replica installs it
            payload = wire.loads(req["blob"])
            self._replicate_record(
                ("import_tablet", req["pred"], payload))
            return {"ok": True, "result": {}}
        if op == "drop_tablet":
            with self.lock:
                self._move_exports.pop(req["pred"], None)
                self._move_staging.pop(req["pred"], None)
            if req.get("move_dst") is not None:
                # post-flip source cleanup: drop AND tombstone, so a
                # stale-routed request gets a typed misroute
                self._replicate_record(
                    ("move_drop", req["pred"], int(req["move_dst"])))
            else:
                self._replicate_record(("drop_attr", req["pred"]))
            return {"ok": True, "result": {}}
        if op == "split_prune":
            # post-flip SPLIT source cleanup: keep only the rows
            # outside the moved hash range (idempotent — pruning an
            # already-pruned tablet removes nothing)
            with self.lock:
                self._move_exports.pop(req["pred"], None)
            self._replicate_record(
                ("split_prune", req["pred"], int(req["nshards"]),
                 int(req["shard"])))
            return {"ok": True, "result": {}}
        if op == "move_export_end":
            # release the cached export blob (aborted/finished move —
            # a multi-GB zlib blob must not sit pinned until the next
            # move of the same predicate)
            with self.lock:
                self._move_exports.pop(req["pred"], None)
                self._move_staging.pop(req["pred"], None)
            return {"ok": True, "result": {}}
        if op == "move_export_begin":
            # streaming move, source side (ref worker/predicate_move
            # .go:81 movePredicateHelper — but with writes LIVE): dump
            # once under the write lock (a consistent cut at snap_ts =
            # max_commit_ts), cache the compressed blob leader-locally,
            # serve it in re-deliverable chunks. Writes resume the
            # moment the dump finishes; everything committed after
            # snap_ts reaches the destination via move_deltas.
            import zlib
            pred = req["pred"]
            chunk = max(1, int(req.get("chunk_bytes", 1 << 20)))
            prefer = int(req.get("prefer_snap_ts", 0) or 0)
            with self.lock:
                exp = self._move_exports.get(pred)
            if exp is not None and prefer \
                    and exp["snap_ts"] == prefer:
                # the driver resumes an interrupted stream: the
                # destination's staged chunks match this cached
                # export, so serve THAT instead of re-dumping (a
                # fresh snap_ts would invalidate every staged chunk
                # and re-pay the dump's write stall)
                return {"ok": True, "result": {
                    "snap_ts": exp["snap_ts"],
                    "bytes": len(exp["blob"]),
                    "chunks": (len(exp["blob"]) + exp["chunk"] - 1)
                    // exp["chunk"]}}
            with self._write_lock:
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    if pred not in self.db.tablets:
                        raise TabletMisrouted(
                            pred, self.db.moved_out.get(pred))
                    payload = self.db.export_tablet_move(
                        pred, int(req.get("nshards", 1) or 1),
                        req.get("shard"))
                    # serialize INSIDE the write lock: for
                    # whole-tablet moves the payload aliases the LIVE
                    # tab.deltas/edge_facets (dump_tablet does not
                    # copy them) — a commit racing the encode would
                    # mutate them mid-iteration
                    raw = wire.dumps(payload)
            blob = zlib.compress(raw, 1)
            with self.lock:
                self._move_exports[pred] = {
                    "snap_ts": payload["snap_ts"], "blob": blob,
                    "chunk": chunk}
            return {"ok": True, "result": {
                "snap_ts": payload["snap_ts"], "bytes": len(blob),
                "chunks": (len(blob) + chunk - 1) // chunk}}
        if op == "move_chunk":
            # one re-deliverable snapshot chunk (offset-keyed by seq);
            # a new source leader has no cache -> the driver re-begins
            failpoint.fire("move.snapshot_chunk")
            pred = req["pred"]
            with self.lock:
                exp = self._move_exports.get(pred)
            if exp is None or exp["snap_ts"] != int(req["snap_ts"]):
                return {"ok": False, "restage": True, "error":
                        f"no active export for {pred!r} at snap_ts "
                        f"{req['snap_ts']} (source leader changed?); "
                        "re-begin"}
            cs = exp["chunk"]
            seq = int(req["seq"])
            return {"ok": True, "result":
                    {"seq": seq,
                     "data": exp["blob"][seq * cs:(seq + 1) * cs]}}
        if op == "move_deltas":
            # catch-up tail, source side: raw EdgeOp batches (whole
            # commits, ascending) from the predicate's change log
            # after the destination's progress offset. LEADER-only:
            # the fence-drain decision needs the head that covers
            # every committed write, and a follower's log may lag.
            from dgraph_tpu.cdc.changelog import OffsetTruncated
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                db = self.db
            try:
                out = db.cdc.read_raw(req["pred"],
                                      after=int(req["after"]),
                                      limit=int(req.get("limit", 512)))
            except OffsetTruncated as e:
                # the bounded log evicted past the destination's
                # base: the driver must re-snapshot from a newer one.
                # `resyncTs` matches the HTTP 410 spelling; the
                # snake_case twin stays for older clients
                return {"ok": False, "error": str(e),
                        "truncated": {"pred": e.pred, "floor": e.floor,
                                      "resyncTs": e.resync_ts,
                                      "resync_ts": e.resync_ts}}
            if req.get("shard") is not None:
                from dgraph_tpu.cluster.shard import filter_ops
                n = int(req.get("nshards", 1) or 1)
                out["batches"] = [
                    (ts, filter_ops(ops, n, int(req["shard"])))
                    for ts, ops in out["batches"]]
            return {"ok": True, "result": out}
        if op == "move_status":
            # source-side fence-drain facts — and the drain's
            # LINEARIZATION BARRIER: every commit on this group runs
            # its ownership check AND its apply under ONE _write_lock
            # hold, so by acquiring _write_lock here (after the fence
            # committed at zero) we know any write that passed its
            # pre-fence ownership check has fully applied (its CDC
            # entry is covered by the `cdc_head` we return), and any
            # write still waiting for the lock will re-check
            # ownership, see the fence, and be rejected. Without this
            # barrier a commit in flight across the fence could land
            # AFTER the drain's last delta read — an acked write
            # silently lost at the flip (review finding). Also
            # reports: any replicated 2PC stage still pending on this
            # predicate (its finalize would land here post-flip).
            pred = req["pred"]
            with self._write_lock:
                with self.lock:
                    if self.node.role != LEADER:
                        raise NotLeader(self.node.leader_id)
                    pending = any(
                        any(p == pred for p, _ in staged)
                        for staged, _k
                        in self.db.pending_txns.values())
                    tab = self.db.tablets.get(pred)
                    mct = tab.max_commit_ts if tab is not None else 0
                    head = self.db.cdc.head(pred)
            return {"ok": True, "result": {"pending_stage": pending,
                                           "max_commit_ts": mct,
                                           "cdc_head": head}}
        if op == "move_stage_chunk":
            # destination side: chunks land in a leader-local staging
            # buffer (NOT replicated — a died leader's staging is
            # simply re-streamed, chunks are re-deliverable)
            pred = req["pred"]
            snap_ts = int(req["snap_ts"])
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                st = self._move_staging.get(pred)
                if st is None or st["snap_ts"] != snap_ts:
                    st = self._move_staging[pred] = {
                        "snap_ts": snap_ts,
                        "total": int(req["total"]), "chunks": {}}
                st["chunks"][int(req["seq"])] = req["data"]
                have = len(st["chunks"])
            return {"ok": True, "result": {"have": have}}
        if op == "move_install":
            # all chunks staged: assemble and replicate the whole
            # tablet as ONE import_tablet record so every group
            # replica installs identical state, then clear staging
            import zlib
            pred = req["pred"]
            snap_ts = int(req["snap_ts"])
            with self.lock:
                st = self._move_staging.get(pred)
                whole = st is not None and st["snap_ts"] == snap_ts \
                    and len(st["chunks"]) >= st["total"]
                blob = b"".join(st["chunks"][i]
                                for i in range(st["total"])) \
                    if whole else b""
            if not whole:
                return {"ok": False, "restage": True, "error":
                        f"staging for {pred!r}@{snap_ts} incomplete "
                        "(destination leader changed?); re-stream"}
            payload = wire.loads(zlib.decompress(blob))

            def move_in_ledger() -> bool:
                if self.zero is None:
                    return True
                got = self.zero.request({"op": "tablet_map"})
                return not got.get("ok") or pred in \
                    got["result"].get("moves", {})
            # an operator abort can race the driver's in-flight
            # stream: its cleanup drop lands, then THIS install would
            # re-create the orphan — and nothing would ever remove
            # it. Check the ledger immediately BEFORE replicating
            # (after the slow decompress, shrinking the TOCTOU) and
            # again AFTER: an abort that slipped between the check
            # and the install gets its orphan dropped right here.
            if not move_in_ledger():
                with self.lock:
                    self._move_staging.pop(pred, None)
                return {"ok": False, "error":
                        f"move of {pred!r} is no longer in zero's "
                        "ledger (aborted?); install refused"}
            self._replicate_record(("import_tablet", pred, payload))
            with self.lock:
                self._move_staging.pop(pred, None)
            if not move_in_ledger():
                self._replicate_record(("drop_attr", pred))
                return {"ok": False, "error":
                        f"move of {pred!r} aborted during install; "
                        "installed copy dropped"}
            return {"ok": True, "result": {
                "max_commit_ts": int(payload["tablet"]
                                     .get("max_commit_ts", 0))}}
        if op == "repl_install":
            # cross-cluster replication install (cluster/replication
            # .py): same staged-chunk assembly as move_install but
            # WITHOUT the zero move-ledger check — the STANDBY's zero
            # has no move entry for a replicated tablet; its cluster-
            # wide write fence is what keeps client writes out, and
            # replication applies land through the replicated-record
            # path below, never the ownership check
            import zlib
            pred = req["pred"]
            snap_ts = int(req["snap_ts"])
            with self.lock:
                st = self._move_staging.get(pred)
                whole = st is not None and st["snap_ts"] == snap_ts \
                    and len(st["chunks"]) >= st["total"]
                blob = b"".join(st["chunks"][i]
                                for i in range(st["total"])) \
                    if whole else b""
            if not whole:
                return {"ok": False, "restage": True, "error":
                        f"staging for {pred!r}@{snap_ts} incomplete "
                        "(standby leader changed?); re-stream"}
            payload = wire.loads(zlib.decompress(blob))
            self._replicate_record(("import_tablet", pred, payload))
            with self.lock:
                self._move_staging.pop(pred, None)
            return {"ok": True, "result": {
                "max_commit_ts": int(payload["tablet"]
                                     .get("max_commit_ts", 0))}}
        if op == "move_apply":
            # catch-up batches landing on the destination, replicated
            # as ONE move_delta record (idempotent: the replicated
            # max_commit_ts guard skips re-delivered commits)
            failpoint.fire("move.catchup")
            pred = req["pred"]
            with self.lock:
                installed = pred in self.db.tablets
            if not installed:
                return {"ok": False, "restage": True, "error":
                        f"tablet {pred!r} not installed here "
                        "(destination leader changed?); re-stream"}
            batches = [(int(ts), list(ops))
                       for ts, ops in req["batches"]]
            if batches:
                self._replicate_record(("move_delta", pred, batches))
            with self.lock:
                tab = self.db.tablets.get(pred)
                mct = tab.max_commit_ts if tab is not None else 0
            return {"ok": True, "result": {"max_commit_ts": mct}}
        if op == "move_dst_status":
            # the driver's resume point after ANY crash: what the
            # destination durably holds (installed tablet + its commit
            # watermark + whether it is a hash-range shard copy — the
            # provenance bit that keeps a stale shard orphan from
            # being adopted as a whole-tablet move's base) and what is
            # merely staged
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                tab = self.db.tablets.get(req["pred"])
                st = self._move_staging.get(req["pred"])
                return {"ok": True, "result": {
                    "installed": tab is not None,
                    "split_partial": req["pred"]
                    in self.db.split_partial,
                    "max_commit_ts": tab.max_commit_ts
                    if tab is not None else 0,
                    "staged_snap_ts": st["snap_ts"] if st else 0,
                    "have_chunks": len(st["chunks"]) if st else 0}}
        if op == "subscribe":
            # CDC long-poll against THIS node's change logs
            # (cdc/changelog.py). Deliberately NOT leader-gated:
            # offsets are deterministic functions of the replicated
            # record stream, so any replica serves the same stream and
            # a subscriber fails over freely — the whole point of the
            # dgchaos CDC nemesis. Also deliberately outside admission
            # (_ADMITTED_OPS): a long-poll parks its serving thread on
            # a condition, not the engine, and must not starve writes.
            from dgraph_tpu.cdc.changelog import OffsetTruncated
            with self.lock:
                db = self.db
            try:
                out = db.cdc.read(
                    str(req.get("pred", "")),
                    after=int(req.get("offset", 0)),
                    limit=int(req.get("limit", 256)),
                    wait_s=float(req.get("wait_ms", 0)) / 1000.0,
                    sub_id=str(req.get("id", "")))
            except OffsetTruncated as e:
                # typed on the wire so ClusterClient.subscribe can
                # re-raise it (not a generic RuntimeError): the
                # re-sync path is client logic. `resyncTs` matches the
                # HTTP 410 spelling (one documented key on BOTH
                # surfaces); the snake_case twin stays for old clients
                return {"ok": False, "error": str(e),
                        "truncated": {"pred": e.pred,
                                      "floor": e.floor,
                                      "resyncTs": e.resync_ts,
                                      "resync_ts": e.resync_ts}}
            return {"ok": True, "result": out}
        if op == "hello":
            # connection-time version negotiation (storage/versions):
            # both sides speak min(protocol)s; the format + build
            # stamps let a rolling upgrade observe the fleet's spread
            from dgraph_tpu.storage.versions import negotiate, \
                versions_payload
            out = versions_payload()
            out["negotiated"] = negotiate(
                int(req.get("protocol_version", 0)))
            return {"ok": True, "result": out}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def watchdog_signals(self) -> dict:
        """Alpha signals: base raft lag/peer silence + the slowest
        CDC subscriber's unread-entry lag."""
        out = super().watchdog_signals()
        with self.lock:
            db = self.db
        try:
            subs = db.cdc.stats().get("subscribers", {})
            lags = [s.get("lag", 0) for s in subs.values()]
            if lags:
                out["cdc_max_lag"] = float(max(lags))
        except Exception:  # noqa: BLE001 — a stats race must not  # dglint: disable=DG07 (watchdog tick provider; no request context)
            pass  # kill the tick
        return out

    def watchdog_context(self) -> dict:
        """Planner/plan-cache state for the incident bundle (NOT the
        full debug_stats: the O(store) tablet walk has no place on a
        capture path that fires mid-incident)."""
        with self.lock:
            db = self.db
        return {
            "planCache": db.plan_cache.stats()
            if db.plan_cache is not None else None,
            "planner": db.planner_impl.stats()
            if db.planner_impl is not None else {"mode": "static"},
            "deviceCache": db.device_cache.stats(),
            "resultCache": db.result_cache.stats()
            if db.result_cache is not None else None,
        }

    def attach_watchdog(self, wd) -> None:
        super().attach_watchdog(wd)
        wd.register_context("engine", self.watchdog_context)

    def debug_stats_payload(self) -> dict:
        """The debug HTTP listener's /debug/stats body: the engine's
        statistics plane + this node's identity and the request ring.
        Same locking posture as the wire `stats` op — self.lock only
        pins the db binding, the walk runs unlocked (debug_stats
        degrades on concurrent-apply races rather than stalling raft)."""
        from dgraph_tpu.utils import reqlog, watchdog
        with self.lock:
            db = self.db
        from dgraph_tpu.storage.versions import versions_payload
        stats = db.debug_stats()
        stats["node"] = self.node_name
        stats["group"] = self.group
        stats["requests"] = reqlog.snapshot()
        stats["netfault"] = netfault.rules()
        stats["lastHeard"] = self.peer_ages()
        stats["alerts"] = watchdog.firing_summary()
        stats["versions"] = versions_payload()
        with self.lock:
            stats["learner"] = self.node.learner
            stats["learnerLag"] = max(0, self.node.commit_index
                                      - self.node.applied_index)
            if self.node.learner:
                metrics.set_gauge("dgraph_learner_lag",
                                  stats["learnerLag"])
        return stats

    def health_payload(self) -> dict:
        out = super().health_payload()
        out["group"] = self.group
        with self._admission:
            out["pending"] = self._inflight
        out["maxPending"] = self.max_pending
        return out


class _MoveDataError(RuntimeError):
    """A tablet move's export/import was REJECTED by a group (vs a
    transient infra error): these count toward the pre-flip abort
    threshold."""


class ZeroServer(RaftServer):
    """The replicated coordinator quorum (dgraph/cmd/zero).

    Unlike the Alpha group, commands execute AT APPLY TIME on every
    replica — the state machine is deterministic, so each member
    computes identical results and the proposer reads its local apply
    result (zero/raft.go:619 applyProposal over the oracle/leases).
    """

    def __init__(self, node_id: int, raft_peers, client_addr,
                 storage=None, move_throttle_mb_s: float = 64.0,
                 move_chunk_bytes: int = 1 << 20,
                 move_fence_lag: int = 16,
                 move_fence_timeout_s: float = 5.0,
                 rebalance_interval_s: float = 0.0,
                 rebalance_band: float = 1.4,
                 split_heat: float = 0.0,
                 rebalance_pin: str = "",
                 rebalance_cooldown_s: float = 120.0,
                 standby_of=None, **kw):
        from dgraph_tpu.cluster.zero import ZeroState
        self.state = ZeroState()
        self.node_name = f"zero-n{node_id}"
        # live-move knobs (docs/deployment.md "Tablet rebalancing"):
        #   move_throttle_mb_s   snapshot streaming budget (bytes/s)
        #   move_fence_lag       fence once catch-up is <= this many
        #                        change-log entries behind
        #   move_fence_timeout_s unfence (writes resume) if the drain
        #                        hasn't converged by then
        self.move_throttle_mb_s = float(move_throttle_mb_s)
        self.move_chunk_bytes = int(move_chunk_bytes)
        self.move_fence_lag = int(move_fence_lag)
        self.move_fence_timeout_s = float(move_fence_timeout_s)
        self.rebalance_interval_s = float(rebalance_interval_s)
        self.rebalance_band = float(rebalance_band)
        self.split_heat = float(split_heat)
        self.rebalance_pin = frozenset(
            p.strip() for p in str(rebalance_pin).split(",")
            if p.strip())
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        super().__init__(node_id, raft_peers, client_addr,
                         storage=storage, **kw)
        # leader-only tablet-move driver: executes the ledger's moves
        # (snapshot stream -> CDC catch-up -> bounded-lag fence ->
        # flip -> source drop/prune), each phase transition
        # raft-persisted so a NEW leader resumes mid-flight moves from
        # the exact phase (ref zero/tablet.go:62 movetablet run by
        # zero's leader). _move_progress is leader-local observability
        # (bytes streamed, lag, fence clock) — recomputed after a
        # leader change, never authoritative.
        self._move_attempts: dict[str, int] = {}
        self._move_progress: dict[str, dict] = {}
        # leader-local cluster alert aggregation (same posture as
        # _move_progress: observability, never replicated): node name
        # -> {"alerts": [...], "age_mono": float} from the firing
        # summaries alphas piggyback on their heat reports
        self._node_alerts: dict[str, dict] = {}
        # node name -> monotonic ts of its last heat/status report:
        # the report_silent watchdog's clock (leader-local too)
        self._node_report_mono: dict[str, float] = {}
        threading.Thread(target=self._move_driver_loop, daemon=True,
                         name=f"zero-moves-{node_id}").start()
        if self.rebalance_interval_s > 0:
            threading.Thread(target=self._rebalance_loop, daemon=True,
                             name=f"zero-rebalance-{node_id}").start()
        # cross-cluster async replication: this zero quorum fronts a
        # STANDBY cluster tailing the primary at `standby_of` (the
        # primary zero's client addrs). Leader-only, like the move
        # driver; the replicated repl_phase/write_fence let a new
        # leader resume (cluster/replication.py)
        self.repl = None
        if standby_of:
            from dgraph_tpu.cluster.replication import ReplicationDriver
            self.repl = ReplicationDriver(self, dict(standby_of))
            threading.Thread(target=self.repl.run, daemon=True,
                             name=f"zero-repl-{node_id}").start()

    def _group_client(self, gid: int):
        """ClusterClient to an alpha group from the membership
        registry (alphas register their client addrs on connect)."""
        from dgraph_tpu.cluster.client import ClusterClient
        with self.lock:
            # learners never lead and never serve writes: the move/
            # replication drivers talk to voters only
            addrs = {rec["id"]: tuple(rec["client"])
                     for rec in self.state.alphas.values()
                     if rec["group"] == gid and not rec.get("learner")}
        return ClusterClient(addrs, timeout=30.0) if addrs else None

    def _move_driver_loop(self, tick_s: float = 0.5):
        while not self._stop.wait(tick_s):
            with self.lock:
                if self.node.role != LEADER:
                    continue
                pending = {p: dict(m)
                           for p, m in self.state.move_queue.items()}
            # counters for moves no longer in the ledger (finished or
            # externally aborted) must not doom a future retry
            for p in list(self._move_attempts):
                if p not in pending:
                    self._move_attempts.pop(p, None)
            for pred, mv in pending.items():
                try:
                    self._drive_move(pred, mv)
                except _MoveDataError as e:
                    # the data phase itself failed (export/import
                    # rejected): count toward the abort threshold —
                    # transient infra errors (registry warm-up, group
                    # elections) retry forever instead
                    log.warning("move_data_retry", pred=pred,
                                error=str(e)[:200])
                    n = self._move_attempts.get(pred, 0) + 1
                    self._move_attempts[pred] = n
                    if n > 20 and mv["phase"] in (
                            "start", "snapshotting", "catching_up",
                            "fenced"):  # any PRE-FLIP phase may abort
                        try:
                            self._abort_move(pred, mv)
                        except Exception:  # noqa: BLE001 — an abort  # dglint: disable=DG07 (zero's move driver is a daemon; no request context)
                            pass  # hiccup must never kill the driver
                except Exception as e:  # noqa: BLE001 — retry next tick  # dglint: disable=DG07 (zero's move driver is a daemon; no request context)
                    log.warning("move_drive_retry", pred=pred,
                                error=str(e)[:200])
                    # post-flip we NEVER abort: the destination owns
                    # the data; keep retrying the source drop forever

    def _abort_move(self, pred: str, mv: dict):
        """Pre-flip abort: route stays with the source (which never
        stopped serving); the copy staged/installed on the destination
        must be dropped or it lives on as a stale orphan. Post-flip
        moves NEVER come here — the destination owns the data."""
        dst_cl = self._group_client(mv["dst"])
        if dst_cl is not None:
            try:
                dst_cl.request({"op": "drop_tablet", "pred": pred})
            except Exception:  # noqa: BLE001 — best-effort cleanup  # dglint: disable=DG07 (move-abort cleanup; no request context)
                pass
            finally:
                dst_cl.close()
        src_cl = self._group_client(mv.get("src", -1))
        if src_cl is not None:
            try:
                # release the source's cached export blob too — an
                # aborted multi-GB move must not pin it until the
                # next move of the same predicate
                src_cl.request({"op": "move_export_end",
                                "pred": pred})
            except Exception:  # noqa: BLE001 — best-effort cleanup  # dglint: disable=DG07 (move-abort cleanup; no request context)
                pass
            finally:
                src_cl.close()
        self.propose_and_wait(("tablet_move_abort", (pred, mv["dst"])))
        with self.lock:
            self._move_attempts.pop(pred, None)
            self._move_progress.pop(pred, None)
        metrics.inc_counter("dgraph_tablet_moves_total",
                            labels={"phase": "aborted"})

    def _advance(self, pred: str, mv: dict, phase: str,
                 snap_ts: int = 0):
        """Commit one phase transition through the quorum; the local
        ledger copy follows only on success, so a deposed leader can
        never act on a phase the quorum rejected."""
        ok, res = self.propose_and_wait(
            ("move_phase", (pred, mv["dst"], phase, int(snap_ts))))
        if not ok or not res:
            raise RuntimeError(f"move phase {phase!r} not committed")
        mv["phase"] = phase
        if snap_ts:
            mv["snap_ts"] = int(snap_ts)
        metrics.inc_counter("dgraph_tablet_moves_total",
                            labels={"phase": phase})
        log.info("move_phase", pred=pred, phase=phase,
                 snap_ts=snap_ts or mv.get("snap_ts", 0))

    def _move_pair(self, mv: dict):
        src_cl = self._group_client(mv["src"])
        dst_cl = self._group_client(mv["dst"])
        if src_cl is None or dst_cl is None:
            if src_cl is not None:
                src_cl.close()
            if dst_cl is not None:
                dst_cl.close()
            raise RuntimeError(
                f"groups {mv['src']}->{mv['dst']} not registered yet")
        return src_cl, dst_cl

    def _drive_move(self, pred: str, mv: dict):
        """One driver pass over a ledger entry — the phase machine
        snapshotting -> catching_up -> fenced -> flipped(-> dropped).
        Every transition is raft-persisted (move_phase /
        tablet_move_done), so a NEW zero leader picks up exactly
        here; the data steps are offset-keyed and re-deliverable, so
        re-driving any phase is idempotent."""
        dst = mv["dst"]
        src = mv.get("src")
        if src is None or src == dst:
            self._abort_move(pred, mv)
            return
        with self.lock:
            prog = self._move_progress.setdefault(
                pred, {"bytes": 0, "lag": None,
                       "started": time.monotonic(),
                       "fence_started": None, "fence_ms": None})
            if prog.get("phase") != mv["phase"]:
                # stuck-in-phase age for the move_stuck watchdog:
                # reset on every phase TRANSITION, so a healthy move
                # marching through phases never looks stuck while a
                # wedged catch-up does
                prog["phase"] = mv["phase"]
                prog["phase_mono"] = time.monotonic()
        if mv["phase"] in ("start", "snapshotting"):
            # ("start" = a legacy pre-phase-machine ledger entry:
            # drive it through the streaming path too)
            if mv["phase"] == "start":
                mv["phase"] = "snapshotting"
            self._phase_snapshot(pred, mv, prog)
        if mv["phase"] == "catching_up":
            self._phase_catchup(pred, mv, prog)
        if mv["phase"] == "fenced":
            self._phase_fenced(pred, mv, prog)
        if mv["phase"] == "flipped":
            self._phase_drop(pred, mv, prog)

    def _phase_snapshot(self, pred: str, mv: dict, prog: dict):
        """Stream the compressed base snapshot source -> destination
        in throttled, re-deliverable chunks. The source serves reads
        AND writes throughout (only the in-memory dump itself briefly
        holds the source's write lock)."""
        src_cl, dst_cl = self._move_pair(mv)
        try:
            st = dst_cl._unwrap(dst_cl.request(
                {"op": "move_dst_status", "pred": pred}))
            if st["installed"]:
                # Resume from the installed copy ONLY when its
                # provenance matches this move: a WHOLE-tablet move
                # must never adopt a shard-only orphan (left by a
                # failed abort cleanup) as its base — post-flip the
                # other shards' rows would be silently gone; and a
                # SPLIT move re-streams rather than trusting an
                # unattributable copy. Mismatches are dropped (the
                # destination is unrouted pre-flip) and re-streamed.
                if mv.get("shard") is None \
                        and not st.get("split_partial"):
                    self._advance(pred, mv, "catching_up",
                                  snap_ts=int(st["max_commit_ts"]))
                    return
                dst_cl.request({"op": "drop_tablet", "pred": pred})
            try:
                begin = src_cl._unwrap(src_cl.request(
                    {"op": "move_export_begin", "pred": pred,
                     "shard": mv.get("shard"),
                     "nshards": mv.get("nshards", 1),
                     # resume an interrupted stream when the source
                     # still caches the export the destination's
                     # staged chunks belong to (chunks are staged
                     # sequentially, so have_chunks IS the resume seq)
                     "prefer_snap_ts": st.get("staged_snap_ts", 0),
                     "chunk_bytes": self.move_chunk_bytes}))
            except RuntimeError as e:
                raise _MoveDataError(str(e)) from e
            snap_ts = int(begin["snap_ts"])
            nchunks = int(begin["chunks"])
            first_seq = 0
            if snap_ts and snap_ts == int(st.get("staged_snap_ts", 0)):
                first_seq = min(int(st.get("have_chunks", 0)), nchunks)
            budget = self.move_throttle_mb_s * 1e6  # bytes/s
            for seq in range(first_seq, nchunks):
                if self._stop.is_set() or not self.is_leader():
                    return
                got = src_cl.request(
                    {"op": "move_chunk", "pred": pred,
                     "snap_ts": snap_ts, "seq": seq})
                if not got.get("ok"):
                    # a new source leader has no export cache: next
                    # driver tick re-begins from a fresh snapshot
                    raise _MoveDataError(
                        f"chunk {seq}: {got.get('error')}")
                data = got["result"]["data"]
                dst_cl._unwrap(dst_cl.request(
                    {"op": "move_stage_chunk", "pred": pred,
                     "snap_ts": snap_ts, "seq": seq,
                     "total": nchunks, "data": data}))
                prog["bytes"] += len(data)
                metrics.inc_counter("dgraph_move_streamed_bytes_total",
                                    len(data))
                if budget > 0 and data:
                    time.sleep(len(data) / budget)  # --move-throttle
            inst = dst_cl.request({"op": "move_install", "pred": pred,
                                   "snap_ts": snap_ts})
            if not inst.get("ok"):
                if inst.get("restage"):
                    return  # dst leader changed mid-stream: re-stream
                raise _MoveDataError(f"install: {inst.get('error')}")
            self._advance(pred, mv, "catching_up", snap_ts=snap_ts)
        finally:
            src_cl.close()
            dst_cl.close()

    def _catchup_once(self, pred: str, mv: dict, prog: dict,
                      src_cl, dst_cl) -> Optional[int]:
        """One catch-up round: read the destination's watermark, pull
        the next raw batch from the source's change log, apply it.
        Returns the lag (entries still behind) or None when the move
        must restart from a fresh snapshot (log truncated / the
        destination lost its copy)."""
        from dgraph_tpu.cdc.changelog import offset_for_ts
        st = dst_cl._unwrap(dst_cl.request(
            {"op": "move_dst_status", "pred": pred}))
        if not st["installed"]:
            self._advance(pred, mv, "snapshotting")
            return None
        after = offset_for_ts(max(int(st["max_commit_ts"]),
                                  int(mv.get("snap_ts", 0))))
        got = src_cl.request(
            {"op": "move_deltas", "pred": pred, "after": after,
             "limit": 512, "shard": mv.get("shard"),
             "nshards": mv.get("nshards", 1)})
        if not got.get("ok"):
            if got.get("truncated"):
                # the bounded change log evicted past our base while
                # we streamed: DROP the destination's stale copy
                # first (it is unrouted pre-flip), then restart from
                # a newer snapshot — leaving it installed would make
                # _phase_snapshot short-circuit straight back to
                # catching_up with the same too-old watermark, a
                # silent snapshotting<->truncated livelock
                dst_cl.request({"op": "drop_tablet", "pred": pred})
                self._advance(pred, mv, "snapshotting")
                return None
            raise _MoveDataError(f"deltas: {got.get('error')}")
        res = got["result"]
        if res["batches"]:
            ap = dst_cl.request({"op": "move_apply", "pred": pred,
                                 "batches": res["batches"]})
            if not ap.get("ok"):
                if ap.get("restage"):
                    self._advance(pred, mv, "snapshotting")
                    return None
                raise _MoveDataError(f"apply: {ap.get('error')}")
        lag = int(res["behind"]) + sum(len(ops) for _, ops
                                       in res["batches"])
        prog["lag"] = int(res["behind"])
        metrics.set_gauge("dgraph_move_catchup_lag", prog["lag"],
                          labels={"pred": pred})
        return 0 if not res["batches"] and not res["behind"] else lag

    def _phase_catchup(self, pred: str, mv: dict, prog: dict):
        """Tail the source's change log until lag falls under the
        fence bound, then fence (a SHORT single-predicate write fence
        — reads never fence)."""
        src_cl, dst_cl = self._move_pair(mv)
        try:
            for _ in range(64):  # bounded per driver tick
                if self._stop.is_set() or not self.is_leader():
                    return
                lag = self._catchup_once(pred, mv, prog, src_cl, dst_cl)
                if lag is None:
                    return  # restarting from snapshot
                if lag <= self.move_fence_lag:
                    failpoint.fire("move.fence")
                    self._advance(pred, mv, "fenced")
                    prog["fence_started"] = time.monotonic()
                    return
            # still far behind: next driver tick continues from the
            # destination's durable watermark
        finally:
            src_cl.close()
            dst_cl.close()

    def _phase_fenced(self, pred: str, mv: dict, prog: dict):
        """Writes to this one predicate are fenced (zero's moving
        mark): drain the last deltas to lag ZERO, verify no 2PC stage
        still pends on the source, then commit the ownership flip. If
        the drain doesn't converge inside the fence budget, UNFENCE —
        writes resume, catch-up continues, nothing is lost."""
        src_cl, dst_cl = self._move_pair(mv)
        try:
            if prog.get("fence_started") is None:
                prog["fence_started"] = time.monotonic()  # resumed
            deadline = prog["fence_started"] + self.move_fence_timeout_s
            while True:
                if self._stop.is_set() or not self.is_leader():
                    return
                lag = self._catchup_once(pred, mv, prog, src_cl,
                                         dst_cl)
                if lag is None:
                    prog["fence_started"] = None
                    return  # restarting from snapshot (unfenced)
                if lag == 0:
                    # the barrier read: move_status acquires the
                    # source's WRITE lock before reading the CDC head,
                    # so any commit that slipped past its pre-fence
                    # ownership check has fully applied and is covered
                    # by cdc_head — the drain is complete only once
                    # the destination's watermark covers that head
                    sst = src_cl._unwrap(src_cl.request(
                        {"op": "move_status", "pred": pred}))
                    st = dst_cl._unwrap(dst_cl.request(
                        {"op": "move_dst_status", "pred": pred}))
                    from dgraph_tpu.cdc.changelog import offset_for_ts
                    covered = offset_for_ts(
                        max(int(st["max_commit_ts"]),
                            int(mv.get("snap_ts", 0))))
                    if not sst["pending_stage"] \
                            and covered >= int(sst["cdc_head"]):
                        break  # fully drained: flip
                if time.monotonic() > deadline:
                    # drain did not converge (pending 2PC stage, write
                    # storm): unfence so the source serves writes
                    # again; catch-up resumes and re-fences later
                    self._advance(pred, mv, "catching_up")
                    prog["fence_started"] = None
                    return
                time.sleep(0.02)
            prog["fence_ms"] = round(
                (time.monotonic() - prog["fence_started"]) * 1000, 1)
            failpoint.fire("move.flip")
            ok, flipped = self.propose_and_wait(
                ("tablet_move_done", (pred, mv["dst"])))
            if not ok or not flipped:
                raise RuntimeError("ownership flip not committed")
            mv["phase"] = "flipped"
            metrics.inc_counter("dgraph_tablet_moves_total",
                                labels={"phase": "flipped"})
            log.info("move_flipped", pred=pred, dst=mv["dst"],
                     fence_ms=prog["fence_ms"])
        finally:
            src_cl.close()
            dst_cl.close()

    def _phase_drop(self, pred: str, mv: dict, prog: dict):
        """Post-flip: the destination owns and serves; retire the
        source copy — whole-tablet moves drop + tombstone (typed
        misroutes for stale clients), split moves prune the moved hash
        range. Idempotent; a resumed leader re-issues freely. NEVER
        aborts — post-flip the destination's copy is the only one
        routed to."""
        src = mv.get("src")
        if src is not None and src != mv["dst"]:
            src_cl = self._group_client(src)
            if src_cl is None:
                raise RuntimeError(f"group {src} unreachable")
            try:
                if mv.get("shard") is not None:
                    resp = src_cl.request(
                        {"op": "split_prune", "pred": pred,
                         "nshards": mv.get("nshards", 2),
                         "shard": mv["shard"]})
                else:
                    resp = src_cl.request(
                        {"op": "drop_tablet", "pred": pred,
                         "move_dst": mv["dst"]})
                if not resp.get("ok") and "not served" not in str(
                        resp.get("error", "")):
                    raise RuntimeError(
                        f"source drop failed: {resp.get('error')}")
            finally:
                src_cl.close()
        self.propose_and_wait(("move_finish", (pred,)))
        with self.lock:
            self._move_attempts.pop(pred, None)
            done = self._move_progress.pop(pred, None)
        if done is not None:
            metrics.observe(
                "dgraph_move_duration_ms",
                (time.monotonic() - done["started"]) * 1000)
        metrics.set_gauge("dgraph_move_catchup_lag", 0,
                          labels={"pred": pred})
        metrics.inc_counter("dgraph_tablet_moves_total",
                            labels={"phase": "dropped"})
        log.info("move_complete", pred=pred, dst=mv["dst"],
                 shard=mv.get("shard"))

    # ------------------------------------------------------ rebalancer

    def _rebalance_loop(self):
        """Leader-only heat-driven rebalancing (ref zero/tablet.go:62
        rebalanceTablets, every --rebalance_interval): each tick feeds
        the replicated stats (heat EWMAs, sizes, tablet map) to the
        pure planner (cluster/rebalance.py) and files at most ONE
        move/split request — the ledger serializes execution, and
        one-step-at-a-time keeps a bad heuristic from thrashing the
        keyspace."""
        from dgraph_tpu.cluster.rebalance import RebalanceConfig, \
            plan_rebalance
        cfg = RebalanceConfig(band=self.rebalance_band,
                              split_heat=self.split_heat,
                              pinned=self.rebalance_pin)
        # leader-local move cooldown: a tablet moved recently is
        # frozen for rebalance_cooldown_s so a heat EWMA still
        # re-equilibrating after the move cannot thrash it straight
        # back (the first bench run moved `knows` 1->2 then 2->1)
        recent: dict[str, float] = {}
        while not self._stop.wait(self.rebalance_interval_s):
            with self.lock:
                if self.node.role != LEADER:
                    continue
                if self.state.move_queue:
                    continue  # one move at a time
                view = {
                    "tablets": dict(self.state.tablets),
                    "splits": {p: dict(s) for p, s
                               in self.state.splits.items()},
                    "moving": dict(self.state.moving),
                    "sizes": dict(self.state.sizes),
                    "heat": dict(self.state.heat),
                    "groups": sorted({rec["group"] for rec
                                      in self.state.alphas.values()}),
                }
            now = time.monotonic()
            for p in list(recent):
                if now - recent[p] > self.rebalance_cooldown_s:
                    del recent[p]
            view["frozen"] = sorted(recent)
            plan = plan_rebalance(view, cfg)
            if plan is None:
                continue
            try:
                ok, accepted = self.propose_and_wait(
                    ("move_request", plan.args()))
                if ok and accepted:
                    recent[plan.pred] = now
                log.info("rebalance_proposed", kind=plan.kind,
                         pred=plan.pred, dst=plan.dst,
                         shard=plan.shard, accepted=bool(ok and
                                                         accepted))
            except Exception as e:  # noqa: BLE001 — keep rebalancing  # dglint: disable=DG07 (rebalancer daemon; no request context)
                log.warning("rebalance_retry", error=str(e)[:200])

    def sm_apply(self, origin, cmd) -> Any:
        return self.state.apply(cmd)

    def sm_snapshot(self):
        return self.state.snapshot()

    def sm_restore(self, snap):
        from dgraph_tpu.cluster.zero import ZeroState
        self.state = ZeroState.from_snapshot(snap)

    def handle_request(self, req: dict) -> dict:
        conf = self.handle_conf_request(req)
        if conf is not None:
            return conf
        op = req.get("op")
        if op == "status":
            with self.lock:
                return {"ok": True, "result": {
                    "id": self.id, "role": self.node.role,
                    "leader": self.node.leader_id,
                    "applied": self.node.applied_index,
                    "max_ts": self.state.max_ts,
                    "next_uid": self.state.next_uid}}
        if op == "tablet_map":
            # routing table read (ref zero.go:410 /state) — leader-only
            # so a lagging follower can never serve a stale map that
            # routes writes to a tablet's old owner after a move.
            # `moving` fences WRITES only (the short fenced phase);
            # `moves` is the live ledger (clients wait on it);
            # `splits` routes hash-range sub-tablets.
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
                return {"ok": True, "result": {
                    "tablets": dict(self.state.tablets),
                    "moving": dict(self.state.moving),
                    "splits": {p: dict(s) for p, s
                               in self.state.splits.items()},
                    "moves": {p: dict(m) for p, m
                              in self.state.move_queue.items()},
                    "sizes": dict(self.state.sizes),
                    # cluster-wide client-write fence + replication
                    # role — every alpha write consults this map, so
                    # the fence takes effect on the NEXT write
                    "fence": self.state.write_fence,
                    "repl_phase": self.state.repl_phase}}
        if op == "cluster_state":
            # membership introspection (ref zero /state) — exposes the
            # split sub-tablet routing and per-tablet heat too
            with self.lock:
                return {"ok": True, "result": {
                    "alphas": {k: dict(v)
                               for k, v in self.state.alphas.items()},
                    "tablets": dict(self.state.tablets),
                    "splits": {p: dict(s) for p, s
                               in self.state.splits.items()},
                    "moves": {p: dict(m) for p, m
                              in self.state.move_queue.items()},
                    "heat": dict(self.state.heat)}}
        if op == "tablet_heat" and "alerts" in req:
            # strip the piggybacked firing-alert summary BEFORE the
            # propose: alert state is leader-local observability
            # (recomputed within one report interval after a leader
            # change), never replicated zero state
            node = str(req.get("alerts_node") or "?")
            with self.lock:
                self._node_report_mono[node] = time.monotonic()
                if req["alerts"]:
                    self._node_alerts[node] = {
                        "alerts": list(req["alerts"]),
                        "seen_mono": time.monotonic()}
                else:
                    self._node_alerts.pop(node, None)
            args = req.get("args", ())
            if not (args and args[0]):
                # pure status heartbeat (no tablets yet / no heat):
                # nothing to fold into the replicated heat EWMA —
                # record the report time, skip the raft propose
                return {"ok": True, "result": {}}
        if op in ("assign_ts", "read_ts", "assign_uids", "commit",
                  "txn_status", "abort_txn", "tablet", "bump_maxes",
                  "tablet_move_start", "tablet_move_done",
                  "tablet_move_abort", "move_request", "move_phase",
                  "tablet_size", "tablet_sizes", "tablet_heat",
                  "connect", "set_write_fence", "repl_phase"):
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
            ok, result = self.propose_and_wait(
                (op, req.get("args", ())))
            if not ok:
                return {"ok": False, "error": "no quorum"}
            return {"ok": True, "result": result}
        if op == "repl_status":
            # per-predicate replication lag (standby zero leader —
            # the driver's progress is leader-local observability)
            if self.repl is None:
                with self.lock:
                    return {"ok": True, "result": {
                        "phase": self.state.repl_phase,
                        "fence": self.state.write_fence,
                        "preds": {}}}
            out = self.repl.lag_payload()
            with self.lock:
                out["fence"] = self.state.write_fence
            return {"ok": True, "result": out}
        if op == "standby_promote":
            # measured-RPO/RTO failover: fence the primary, drain to
            # its post-fence CDC heads, flip this cluster writable
            if self.repl is None:
                return {"ok": False, "error":
                        "this zero is not a standby (--standby-of)"}
            with self.lock:
                if self.node.role != LEADER:
                    raise NotLeader(self.node.leader_id)
            from dgraph_tpu.cluster.replication import PromoteError
            try:
                out = self.repl.promote(
                    force=bool(req.get("force", False)))
            except PromoteError as e:
                return {"ok": False, "error": str(e)}
            return {"ok": True, "result": out}
        if op == "hello":
            # same negotiation surface as alphas (storage/versions)
            from dgraph_tpu.storage.versions import negotiate, \
                versions_payload
            out = versions_payload()
            out["negotiated"] = negotiate(
                int(req.get("protocol_version", 0)))
            return {"ok": True, "result": out}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _cluster_alerts(self) -> dict:
        """The leader-local aggregation of piggybacked alpha alerts
        (stale entries age out at 3 heat intervals: a dead node's
        last report must not look firing forever)."""
        ttl = 3 * 30.0
        try:
            import os as _os
            ttl = 3 * float(_os.environ.get(
                "DGRAPH_TPU_HEAT_INTERVAL_S", "") or 30.0)
        except ValueError:
            pass
        now = time.monotonic()
        with self.lock:
            for n in [n for n, rec in self._node_alerts.items()
                      if now - rec["seen_mono"] > ttl]:
                del self._node_alerts[n]
            return {n: {"alerts": list(rec["alerts"]),
                        "age_s": round(now - rec["seen_mono"], 1)}
                    for n, rec in sorted(self._node_alerts.items())}

    def _alerts_extra(self) -> dict:
        return {"cluster": self._cluster_alerts()}

    def watchdog_signals(self) -> dict:
        """Zero signals: base + the oldest move/split phase age (the
        move_stuck watchdog; ages come from the replicated ledger's
        phase_mono the leader's driver refreshes)."""
        out = super().watchdog_signals()
        now = time.monotonic()
        with self.lock:
            ages = [now - p["phase_mono"]
                    for p in self._move_progress.values()
                    if p.get("phase_mono") is not None]
            if self.node.role != LEADER:
                # alphas report to the LEADER only: a demoted zero's
                # stale report clock would age into a false fire —
                # drop it so a re-election starts a fresh one
                self._node_report_mono.clear()
            reports = [now - t
                       for t in self._node_report_mono.values()]
        if ages:
            out["move_stuck_age_s"] = max(ages)
        if reports:
            # the quietest alpha's report gap — the node-down /
            # partitioned-from-zero signal (works at replicas=1,
            # where raft_peer_silent has no peers to time)
            out["report_silent_s"] = max(reports)
        return out

    def debug_stats_payload(self) -> dict:
        """Zero's /debug/stats: base payload + the live move ledger
        enriched with the leader's driver progress (bytes streamed,
        catch-up lag, fence clock) and the heat table — what the dgtop
        MOVES panel renders."""
        from dgraph_tpu.storage.versions import versions_payload
        out = super().debug_stats_payload()
        out["versions"] = versions_payload()
        with self.lock:
            moves = {p: dict(m) for p, m
                     in self.state.move_queue.items()}
            out["splits"] = {p: dict(s) for p, s
                             in self.state.splits.items()}
            out["heat"] = dict(self.state.heat)
            out["tablets_map"] = dict(self.state.tablets)
            role = self.node.role
            prog_snap = {p: dict(m)
                         for p, m in self._move_progress.items()}
        for pred, mv in moves.items():
            prog = prog_snap.get(pred) or {}
            mv["bytes"] = prog.get("bytes", 0)
            mv["lag"] = prog.get("lag")
            mv["fence_ms"] = prog.get("fence_ms")
            if prog.get("fence_started") is not None \
                    and mv["fence_ms"] is None:
                mv["fence_ms"] = round(
                    (time.monotonic() - prog["fence_started"]) * 1e3, 1)
        out["moves"] = moves
        out["role"] = role
        with self.lock:
            phase = self.state.repl_phase
            fence = self.state.write_fence
        if self.repl is not None:
            out["replication"] = self.repl.lag_payload()
            out["replication"]["fence"] = fence
        elif phase or fence:
            # a fenced/promoted cluster without a driver (an old
            # primary after failover) still surfaces its role
            out["replication"] = {"phase": phase, "fence": fence,
                                  "preds": {}}
        return out
