"""Typed cluster routing errors shared by server and client sides.

Lives in its own module so cluster/service.py (raises) and
cluster/client.py (re-raises from the wire) can both import it without
a cycle.
"""

from __future__ import annotations

from typing import Optional


class TabletMisrouted(RuntimeError):
    """The serving group no longer serves this tablet (it moved, or
    split, after the caller fetched its routing map). RETRYABLE by
    contract: the router refreshes the tablet map and re-routes
    (bounded retries) — a user must never see this as a 500.

    Crosses the wire as {"ok": False, "misrouted": {"pred", "group"}}
    (cluster/service.py _client_loop -> cluster/client.py _unwrap)."""

    def __init__(self, pred: str, group: Optional[int] = None,
                 msg: str = ""):
        self.pred = pred
        self.group = group  # new owner if known, else None
        super().__init__(
            msg or f"tablet {pred!r} is not served here"
            + (f" (moved to group {group})" if group else "")
            + "; refresh the tablet map and re-route")


class StaleRead(RuntimeError):
    """A watermark-bounded follower read could not be served: this
    replica's applied watermark has not yet covered the read's granted
    `read_ts` within the staleness bound. RETRYABLE by contract — the
    router retries the read on another replica of the same group (a
    voter, or ultimately the leader, always qualifies) instead of
    surfacing an error or, worse, serving a snapshot older than the
    granted timestamp.

    Crosses the wire as {"ok": False, "stale": {"readTs", "watermark"}}
    (cluster/service.py _client_loop -> cluster/client.py _unwrap)."""

    def __init__(self, read_ts: int, watermark: int, msg: str = ""):
        self.read_ts = read_ts
        self.watermark = watermark
        super().__init__(
            msg or f"replica watermark {watermark} has not reached "
            f"read_ts {read_ts}; retry the read on another replica")


class WriteFenced(RuntimeError):
    """The WHOLE cluster refuses client writes: it is a replication
    standby (state arrives only through the replication stream,
    cluster/replication.py) or a fenced old primary after a standby
    promotion. Reads keep serving. NOT retryable against this
    cluster — the client must re-point at the promoted primary.

    Crosses the wire as {"ok": False, "fenced": {"phase"}}
    (cluster/service.py _client_loop -> cluster/client.py _unwrap)."""

    def __init__(self, phase: str = "", msg: str = ""):
        self.phase = phase
        super().__init__(
            msg or "cluster is write-fenced"
            + (f" (replication phase {phase!r})" if phase else "")
            + ": client writes are refused; "
            "direct writes at the active primary")


# Typed-wire-error registry (dglint DG14): every typed error this
# module defines MUST have a wire serialization arm in
# cluster/service.py _client_loop (an `except Cls` producing the
# listed response key) AND a client re-raise in cluster/client.py
# ClusterClient._unwrap (a `resp.get(key)` branch raising Cls) — a
# typed error missing either half silently degrades to a bare
# RuntimeError 500 at the far edge, which is exactly the
# read-parity/retry-contract bug the types exist to prevent.
WIRE_ERRORS = (
    ("TabletMisrouted", "misrouted"),
    ("StaleRead", "stale"),
    ("WriteFenced", "fenced"),
)
