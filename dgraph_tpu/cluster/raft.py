"""Raft consensus — replication for Alpha groups and the Zero quorum.

The reference replicates every shard ("group") and the Zero coordinator
through etcd's Raft library (worker/draft.go, dgraph/cmd/zero/raft.go,
conn/node.go glue, raftwal/storage.go persistence). This is our own
implementation of the same protocol, shaped like etcd's raft rather than
a thread-per-timer design: a `RaftNode` is a pure tick-driven state
machine — the container calls `tick()` on a logical clock, `step(msg)`
for inbound messages, `propose(data)` for client writes, and drains
`ready()` for (messages to send, entries to persist, entries to apply).
That makes elections, partitions, and crash-replay deterministic in
tests (no wall clock, no sleeps), mirroring how the reference's Run
loops pump etcd raft's Ready channel (worker/draft.go:760).

Persistence uses the native C++ KV store (native/native.cc) when built:
hardstate + log entries + snapshot survive restart the way
raftwal.DiskStorage persists to Badger (raftwal/storage.go:37).

Log compaction: `take_snapshot(data, index)` truncates the log below
`index` and stores an application snapshot; followers too far behind
receive InstallSnapshot (ref worker/snapshot.go:107 streamed snapshots,
raft.go MsgSnap path).
"""

from __future__ import annotations

import random

from dgraph_tpu.wire import dumps as wire_dumps
from dgraph_tpu.wire import loads_compat as _wire_load

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# message types
VOTE_REQ = "vote_req"
VOTE_RESP = "vote_resp"
APPEND_REQ = "append_req"
APPEND_RESP = "append_resp"
SNAP_REQ = "snap_req"
SNAP_RESP = "snap_resp"
GOODBYE = "goodbye"  # "you were conf-removed" notice to a non-member


@dataclass
class Entry:
    term: int
    index: int
    data: Any


@dataclass
class Msg:
    type: str
    frm: int
    to: int
    term: int
    # vote
    last_log_index: int = 0
    last_log_term: int = 0
    granted: bool = False
    # append
    prev_index: int = 0
    prev_term: int = 0
    entries: list = field(default_factory=list)
    commit: int = 0
    success: bool = False
    match_index: int = 0
    reject_hint: int = 0
    # snapshot
    snap_index: int = 0
    snap_term: int = 0
    snap_data: Any = None


@dataclass
class Ready:
    msgs: list
    committed: list          # entries newly safe to apply
    soft_state: tuple        # (role, leader_id)
    snapshot: Optional[tuple] = None  # (index, term, data) to restore


class MemoryStorage:
    """Volatile storage (tests); interface shared with DiskStorage."""

    def __init__(self):
        self.term = 0
        self.voted_for = None
        self.entries: list[Entry] = []
        self.snap_index = 0
        self.snap_term = 0
        self.snap_data = None

    def save_hardstate(self, term: int, voted_for: Optional[int]):
        self.term = term
        self.voted_for = voted_for

    def append(self, entries: list[Entry]):
        if entries:
            first = entries[0].index
            self.entries = [e for e in self.entries if e.index < first]
            self.entries.extend(entries)

    def save_snapshot(self, index: int, term: int, data: Any):
        self.snap_index = index
        self.snap_term = term
        self.snap_data = data
        self.entries = [e for e in self.entries if e.index > index]

    def save_members(self, members: dict):
        """Persist the conf-changed membership map so a restart keeps
        it instead of reverting to the CLI's --raft-peers (volatile
        storage: no-op)."""

    def load_members(self) -> Optional[dict]:
        return None

    def flush(self):
        pass

    def close(self):
        pass


class DiskStorage(MemoryStorage):
    """Raft persistence over the native KV store (the raftwal role:
    raftwal/storage.go keys entry/hardstate/snapshot per node)."""

    def __init__(self, directory: str, sync: bool = False):
        super().__init__()
        from dgraph_tpu import native
        if native.available():
            self._kv = native.NativeKV(directory, sync)
        else:
            from dgraph_tpu.storage.kvfallback import PyKV
            self._kv = PyKV(directory, sync)
        hs = self._kv.get(b"hs")
        if hs is not None:
            self.term, self.voted_for = _wire_load(hs)
        sn = self._kv.get(b"snap")
        if sn is not None:
            self.snap_index, self.snap_term, self.snap_data = \
                _wire_load(sn)
        for k, v in self._kv.scan(b"e/"):
            e = _wire_load(v)
            if e.index > self.snap_index:
                self.entries.append(e)
        self.entries.sort(key=lambda e: e.index)

    def save_hardstate(self, term, voted_for):
        super().save_hardstate(term, voted_for)
        self._kv.put(b"hs", wire_dumps((term, voted_for)))

    def append(self, entries):
        if not entries:
            return
        prev_last = self.entries[-1].index if self.entries \
            else self.snap_index
        super().append(entries)
        for e in entries:
            self._kv.put(b"e/%016x" % e.index, wire_dumps(e))
        # conflict truncation shrank the log: stale persisted entries
        # above the new tail must go too, or a restart resurrects a
        # deposed leader's discarded suffix
        for idx in range(entries[-1].index + 1, prev_last + 1):
            self._kv.delete(b"e/%016x" % idx)

    def save_snapshot(self, index, term, data):
        # persist the snapshot record FIRST: a crash between the two
        # steps must never leave neither entries nor snapshot (recovery
        # skips log keys <= snap_index anyway)
        self._kv.put(b"snap", wire_dumps((index, term, data)))
        # then drop log keys below it, like raftwal truncation
        # (raftwal/storage.go:594 CreateSnapshot)
        for k, _ in list(self._kv.scan(b"e/")):
            if int(k[2:], 16) <= index:
                self._kv.delete(k)
        super().save_snapshot(index, term, data)
        if hasattr(self._kv, "snapshot"):
            self._kv.snapshot()

    def save_members(self, members: dict):
        self._kv.put(b"members", wire_dumps(members))

    def load_members(self) -> Optional[dict]:
        raw = self._kv.get(b"members")
        return _wire_load(raw) if raw is not None else None

    def flush(self):
        if hasattr(self._kv, "flush"):
            self._kv.flush()

    def close(self):
        self._kv.close()


class RaftNode:
    """One member of a Raft group. Pure state machine, no IO."""

    def __init__(self, node_id: int, peers: list[int],
                 storage: Optional[MemoryStorage] = None,
                 election_ticks: int = 10, heartbeat_ticks: int = 2,
                 rng: Optional[random.Random] = None,
                 max_batch: int = 64, learner: bool = False):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        # Non-voting members (etcd raft "learners", ref raft.go
        # ProgressTracker.Learners): they receive the replicated log but
        # never campaign, vote, or count toward the commit quorum.
        self.learners: set[int] = set()
        self.learner = learner
        self.storage = storage or MemoryStorage()
        self.rng = rng or random.Random(node_id * 7919)
        self.election_ticks = election_ticks
        self.heartbeat_ticks = heartbeat_ticks
        self.max_batch = max_batch

        self.term = self.storage.term
        self.voted_for = self.storage.voted_for
        self.log: list[Entry] = list(self.storage.entries)
        self.snap_index = self.storage.snap_index
        self.snap_term = self.storage.snap_term

        self.role = FOLLOWER
        self.leader_id: Optional[int] = None
        self.removed = False  # this node was conf-removed: stop
        #                       campaigning/heartbeating, serve reads only
        self.commit_index = self.snap_index
        self.applied_index = self.snap_index
        self.votes: set[int] = set()
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        self.elapsed = 0
        self.timeout = self._rand_timeout()

        self._msgs: list[Msg] = []
        self._pending_snapshot: Optional[tuple] = None
        # restore-from-disk: surface the persisted snapshot to the app
        if self.storage.snap_data is not None:
            self._pending_snapshot = (self.snap_index, self.snap_term,
                                      self.storage.snap_data)

    # ---------------------------------------------------------------- log

    def _rand_timeout(self) -> int:
        return self.election_ticks + self.rng.randrange(self.election_ticks)

    def last_index(self) -> int:
        return self.log[-1].index if self.log else self.snap_index

    def last_term(self) -> int:
        return self.log[-1].term if self.log else self.snap_term

    def _entry(self, index: int) -> Optional[Entry]:
        off = index - self.snap_index - 1
        if 0 <= off < len(self.log):
            return self.log[off]
        return None

    def _term_at(self, index: int) -> Optional[int]:
        if index == self.snap_index:
            return self.snap_term
        e = self._entry(index)
        return e.term if e else None

    # ------------------------------------------------------------- driving

    def tick(self):
        if self.removed:
            return
        self.elapsed += 1
        if self.role == LEADER:
            if self.elapsed >= self.heartbeat_ticks:
                self.elapsed = 0
                self._broadcast_append()
        elif self.elapsed >= self.timeout:
            if self.learner:
                # learners never campaign; a silent leader just means
                # we wait for the next append
                self.elapsed = 0
            else:
                self._campaign()

    def propose(self, data: Any) -> bool:
        """Leader-only append; returns False when not leader (caller
        forwards to leader_id, ref worker/proposal.go routing)."""
        if self.role != LEADER:
            return False
        e = Entry(self.term, self.last_index() + 1, data)
        self.log.append(e)
        self.storage.append([e])
        self.match_index[self.id] = e.index
        if not self.peers:  # single-voter group commits immediately
            self._advance_commit()
        if self.peers or self.learners:
            self._broadcast_append()
        return True

    def step(self, m: Msg):
        if m.term > self.term:
            self._become_follower(m.term,
                                  m.frm if m.type == APPEND_REQ else None)
        handler = {
            VOTE_REQ: self._on_vote_req,
            VOTE_RESP: self._on_vote_resp,
            APPEND_REQ: self._on_append_req,
            APPEND_RESP: self._on_append_resp,
            SNAP_REQ: self._on_snap_req,
            SNAP_RESP: self._on_snap_resp,
        }[m.type]
        handler(m)

    def ready(self) -> Ready:
        msgs, self._msgs = self._msgs, []
        committed = []
        while self.applied_index < self.commit_index:
            self.applied_index += 1
            e = self._entry(self.applied_index)
            if e is not None:
                committed.append(e)
        snap, self._pending_snapshot = self._pending_snapshot, None
        if snap is not None:
            self.applied_index = max(self.applied_index, snap[0])
        return Ready(msgs, committed, (self.role, self.leader_id), snap)

    def take_snapshot(self, data: Any, index: Optional[int] = None):
        """App-driven checkpoint: compact the log below `index`
        (defaults to applied). Ref worker/draft.go:1206
        calculateSnapshot + raftwal truncation."""
        index = self.applied_index if index is None else index
        if index <= self.snap_index:
            return
        term = self._term_at(index)
        self.storage.save_snapshot(index, term, data)
        self.log = [e for e in self.log if e.index > index]
        self.snap_index = index
        self.snap_term = term

    # -------------------------------------------------- membership changes
    # Applied at COMMIT time, one change in flight at a time (the etcd
    # model; ref conn.Node conf changes + zero/raft.go member proposals).

    def add_peer(self, p: int):
        if p == self.id:
            self.learner = False  # promotion to voter
            return
        if p in self.peers:
            return
        promoted = p in self.learners
        self.learners.discard(p)
        self.peers.append(p)
        if self.role == LEADER:
            if not promoted:  # a promoted learner keeps its progress
                self.next_index[p] = self.last_index() + 1
                self.match_index[p] = 0
                self._send_append(p)
            self._advance_commit()  # the quorum just grew

    def add_learner(self, p: int):
        """Add a non-voting member: replicated to, never counted."""
        if p == self.id:
            self.learner = True
            return
        if p in self.peers or p in self.learners:
            return
        self.learners.add(p)
        if self.role == LEADER:
            self.next_index[p] = self.last_index() + 1
            self.match_index[p] = 0
            self._send_append(p)

    def remove_peer(self, p: int):
        if p == self.id:
            # self-removal: step down and go quiet; the rest of the
            # cluster stops heartbeating us (ref /removeNode semantics)
            self.removed = True
            if self.role == LEADER:
                self.role = FOLLOWER
                self.leader_id = None
            return
        if p in self.peers:
            self.peers.remove(p)
        self.learners.discard(p)
        self.next_index.pop(p, None)
        self.match_index.pop(p, None)
        self.votes.discard(p)
        if self.role == LEADER:
            self._advance_commit()  # the quorum just shrank

    # ------------------------------------------------------------ internal

    def _become_follower(self, term: int, leader: Optional[int]):
        if term > self.term:
            # votes are per-term: a term bump always clears ours,
            # whatever triggered it (vote req or append from new leader)
            self.voted_for = None
        self.term = term
        self.role = FOLLOWER
        self.leader_id = leader
        self.votes = set()
        self.elapsed = 0
        self.timeout = self._rand_timeout()
        self.storage.save_hardstate(self.term, self.voted_for)

    def _campaign(self):
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.id
        self.leader_id = None
        self.votes = {self.id}
        self.elapsed = 0
        self.timeout = self._rand_timeout()
        self.storage.save_hardstate(self.term, self.voted_for)
        if not self.peers:
            self._become_leader()
            return
        for p in self.peers:
            self._msgs.append(Msg(VOTE_REQ, self.id, p, self.term,
                                  last_log_index=self.last_index(),
                                  last_log_term=self.last_term()))

    def _become_leader(self):
        self.role = LEADER
        self.leader_id = self.id
        reps = self._replicas()
        self.next_index = {p: self.last_index() + 1 for p in reps}
        self.match_index = {p: 0 for p in reps}
        self.match_index[self.id] = self.last_index()
        # noop entry to commit entries from prior terms (§5.4.2)
        e = Entry(self.term, self.last_index() + 1, None)
        self.log.append(e)
        self.storage.append([e])
        self.match_index[self.id] = e.index
        if not self.peers:
            self._advance_commit()
        if reps:
            self._broadcast_append()

    def _on_vote_req(self, m: Msg):
        up_to_date = (m.last_log_term, m.last_log_index) >= \
            (self.last_term(), self.last_index())
        grant = (m.term >= self.term and up_to_date
                 and self.voted_for in (None, m.frm)
                 and self.role != LEADER
                 and not self.learner)  # learners never vote
        if grant:
            self.voted_for = m.frm
            self.elapsed = 0
            self.storage.save_hardstate(self.term, self.voted_for)
        self._msgs.append(Msg(VOTE_RESP, self.id, m.frm, self.term,
                              granted=grant))

    def _on_vote_resp(self, m: Msg):
        if self.role != CANDIDATE or m.term < self.term:
            return
        # only votes from the CURRENT configuration count toward the
        # quorum — a stale ex-member's grant must never let two
        # candidates both reach "majority" in one term
        if m.granted and (m.frm in self.peers or m.frm == self.id):
            self.votes.add(m.frm)
            if len(self.votes) * 2 > len(self.peers) + 1:
                self._become_leader()

    def _replicas(self) -> list[int]:
        """Everyone the leader replicates to: voters + learners."""
        return list(self.peers) + sorted(self.learners)

    def _broadcast_append(self):
        for p in self._replicas():
            self._send_append(p)

    def _send_append(self, p: int):
        nxt = self.next_index.get(p, self.last_index() + 1)
        if nxt <= self.snap_index:
            # follower needs state we compacted away: ship the snapshot
            self._msgs.append(Msg(
                SNAP_REQ, self.id, p, self.term,
                snap_index=self.snap_index, snap_term=self.snap_term,
                snap_data=self.storage.snap_data, commit=self.commit_index))
            return
        prev = nxt - 1
        prev_term = self._term_at(prev)
        if prev_term is None:
            prev_term = 0
        off = nxt - self.snap_index - 1  # log is contiguous from snap+1
        ents = self.log[off: off + self.max_batch]
        self._msgs.append(Msg(APPEND_REQ, self.id, p, self.term,
                              prev_index=prev, prev_term=prev_term,
                              entries=ents, commit=self.commit_index))

    def _on_append_req(self, m: Msg):
        if m.term < self.term:
            self._msgs.append(Msg(APPEND_RESP, self.id, m.frm, self.term,
                                  success=False))
            return
        self.role = FOLLOWER
        self.leader_id = m.frm
        self.elapsed = 0
        # a quiet joiner (started with removed=True while waiting for
        # its conf-change) wakes on the first append from the leader —
        # that message proves it is now a member. Genuinely removed
        # nodes never receive appends (they left every member's peers).
        self.removed = False
        local_prev_term = self._term_at(m.prev_index)
        if m.prev_index > self.last_index() or (
                local_prev_term is not None
                and local_prev_term != m.prev_term):
            hint = min(m.prev_index, self.last_index() + 1)
            self._msgs.append(Msg(APPEND_RESP, self.id, m.frm, self.term,
                                  success=False, reject_hint=hint))
            return
        if local_prev_term is None:
            # prev falls below our snapshot: entries <= snap_index are
            # already applied; accept the overlap from snap_index on
            m.entries = [e for e in m.entries if e.index > self.snap_index]
        new = []
        for e in m.entries:
            have = self._entry(e.index)
            if have is not None and have.term != e.term:
                self.log = [x for x in self.log if x.index < e.index]
                have = None
            if have is None:
                new.append(e)
        if new:
            self.log.extend(new)
            self.storage.append(new)
        if m.commit > self.commit_index:
            self.commit_index = min(m.commit, self.last_index())
        self._msgs.append(Msg(APPEND_RESP, self.id, m.frm, self.term,
                              success=True,
                              match_index=m.prev_index + len(m.entries)))

    def _on_append_resp(self, m: Msg):
        if self.role != LEADER or m.term < self.term:
            return
        if m.success:
            self.match_index[m.frm] = max(
                self.match_index.get(m.frm, 0), m.match_index)
            self.next_index[m.frm] = self.match_index[m.frm] + 1
            self._advance_commit()
            if self.next_index[m.frm] <= self.last_index():
                self._send_append(m.frm)  # keep streaming the backlog
        else:
            hint = m.reject_hint if m.reject_hint else \
                self.next_index.get(m.frm, 2) - 1
            self.next_index[m.frm] = max(1, hint)
            self._send_append(m.frm)

    def _advance_commit(self):
        """Commit = highest index replicated on a majority with an entry
        from the current term (§5.4.2)."""
        n_members = len(self.peers) + 1
        for idx in range(self.last_index(), self.commit_index, -1):
            if self._term_at(idx) != self.term:
                break
            # learners' progress must never inflate the quorum count
            count = sum(1 for p, mi in self.match_index.items()
                        if mi >= idx and (p == self.id or p in self.peers))
            if count * 2 > n_members:
                self.commit_index = idx
                break

    def _on_snap_req(self, m: Msg):
        if m.term < self.term:
            return
        self.role = FOLLOWER
        self.leader_id = m.frm
        self.elapsed = 0
        if m.snap_index <= self.snap_index:
            self._msgs.append(Msg(SNAP_RESP, self.id, m.frm, self.term,
                                  match_index=self.snap_index))
            return
        self.storage.save_snapshot(m.snap_index, m.snap_term, m.snap_data)
        self.log = []
        self.snap_index = m.snap_index
        self.snap_term = m.snap_term
        self.commit_index = max(self.commit_index, m.snap_index)
        self.applied_index = m.snap_index
        self._pending_snapshot = (m.snap_index, m.snap_term, m.snap_data)
        self._msgs.append(Msg(SNAP_RESP, self.id, m.frm, self.term,
                              match_index=m.snap_index))

    def _on_snap_resp(self, m: Msg):
        if self.role != LEADER:
            return
        self.match_index[m.frm] = max(self.match_index.get(m.frm, 0),
                                      m.match_index)
        self.next_index[m.frm] = self.match_index[m.frm] + 1
