"""TCP transport for Raft messages: the conn/ tier.

The reference moves Raft traffic over gRPC streams with pooled
connections (conn/pool.go:45 Pool, conn/node.go:48 send loops,
conn/raft_server.go:126 RaftMessage handler). Here the same role is a
length-prefixed wire-frame protocol over plain TCP: one listener per
node, one lazily-dialed persistent connection per peer, best-effort
send (Raft tolerates drops; the protocol retries by design).

This plugs into the Msg seam cluster/raft.py promises: anything that
can deliver `Msg` objects can drive a RaftNode — the SimCluster bus in
tests, this transport in real deployments.
"""

from __future__ import annotations

import socket
import threading
from typing import Callable, Optional

from dgraph_tpu import wire
from dgraph_tpu.cluster.raft import Msg
from dgraph_tpu.utils import failpoint, netfault
from dgraph_tpu.utils.metrics import inc_counter

_HELLO = b"DGTRAFT1"


class TcpTransport:
    """Raft Msg delivery over TCP (peer id -> (host, port) map)."""

    def __init__(self, node_id: int, peers: dict[int, tuple[str, int]],
                 on_msg: Callable[[Msg], None]):
        self.id = node_id
        self.peers = dict(peers)
        self.on_msg = on_msg
        self._out: dict[int, socket.socket] = {}
        self._out_lock = threading.Lock()
        self._closed = threading.Event()
        host, port = self.peers[node_id]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.addr = self._listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"raft-accept-{node_id}",
            daemon=True)

    def start(self):
        """Begin accepting inbound connections. Separate from __init__
        so the owner can finish wiring (e.g. assign the transport
        attribute its on_msg handler reads) before messages arrive."""
        self._accept_thread.start()

    # ------------------------------------------------------------ inbound

    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._read_loop, args=(conn,),
                             daemon=True).start()

    def _read_loop(self, conn: socket.socket):
        try:
            if wire.read_frame(conn) != _HELLO:
                return
            while not self._closed.is_set():
                msg = wire.loads(wire.read_frame(conn))
                if isinstance(msg, Msg):
                    self.on_msg(msg)
        except (EOFError, OSError, wire.WireError):
            pass
        finally:
            conn.close()

    # ----------------------------------------------------------- outbound

    def send(self, msg: Msg) -> bool:
        """Best-effort: one attempt over the pooled conn, one redial.
        Raft's own retry logic (heartbeats, append retries) recovers
        from drops, like the reference's conn.Pool send failures."""
        if self._closed.is_set():
            return False
        try:
            # chaos seam: an armed `transport.send` failpoint delays
            # (sleep) or drops (error) outbound Raft frames — the
            # deterministic in-process flaky-network nemesis
            failpoint.fire("transport.send")
        except failpoint.FailpointError:
            inc_counter("raft_send_drops")
            return False
        dup = False
        if netfault.armed():
            # network fault plane (utils/netfault.py): the armed rule
            # table models this link — drop eats the frame (Raft's own
            # retries recover, exactly like a lossy wire), delay slept
            # inside act(), DUP sends the idempotent frame twice
            addr = self.peers.get(msg.to)
            verdict = netfault.act(addr) if addr is not None else None
            if verdict == netfault.DROP:
                inc_counter("raft_send_drops")
                return False
            dup = verdict == netfault.DUP
        for attempt in (0, 1):
            sock = self._conn_to(msg.to, force_new=attempt == 1)
            if sock is None:
                inc_counter("raft_send_drops")
                return False
            try:
                wire.write_frame(sock, wire.dumps(msg))
                if dup:
                    wire.write_frame(sock, wire.dumps(msg))
                return True
            except OSError:
                self._drop_conn(msg.to)
        inc_counter("raft_send_drops")
        return False

    def _conn_to(self, peer: int,
                 force_new: bool = False) -> Optional[socket.socket]:
        with self._out_lock:
            sock = self._out.get(peer)
            if sock is not None and not force_new:
                return sock
            if sock is not None:
                sock.close()
                del self._out[peer]
            addr = self.peers.get(peer)
        if addr is None:
            return None
        # dial OUTSIDE the lock (dglint DG04): a 1s connect timeout to
        # one dead peer must not serialize sends to every healthy peer
        try:
            sock = socket.create_connection(addr, timeout=1.0)
            sock.settimeout(5.0)
            wire.write_frame(sock, _HELLO)
        except OSError:
            return None
        with self._out_lock:
            if self._closed.is_set():
                sock.close()
                return None
            cur = self._out.get(peer)
            if cur is not None:
                # a racing dialer won; keep ONE pooled conn per peer
                sock.close()
                return cur
            self._out[peer] = sock
            return sock

    def _drop_conn(self, peer: int):
        with self._out_lock:
            sock = self._out.pop(peer, None)
        if sock is not None:
            sock.close()

    # -------------------------------------------------------------- close

    def close(self):
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_lock:
            for sock in self._out.values():
                sock.close()
            self._out.clear()
