"""Cluster control plane: coordinator (Zero-equivalent), membership,
replication. Round 1 ships the in-process coordinator; the gRPC/DCN
service wrapping and Raft replication layer over it."""

from dgraph_tpu.cluster.coordinator import Coordinator, TxnAborted
