"""Coordinator: timestamps, UID leases, transaction oracle, tablet map.

Re-provides Dgraph Zero's core services (dgraph/cmd/zero/):
  - monotonically increasing timestamps     (zero/assign.go:64 lease)
  - UID block leases                        (zero/assign.go:158 AssignUids)
  - commit/abort with conflict detection    (zero/oracle.go:326 commit,
                                             oracle.go:76 hasConflict)
  - tablet -> group ownership               (zero/zero.go:564 ShouldServe)

Design difference from the reference: Zero is a separate Raft-replicated
process streaming OracleDeltas to every Alpha group
(zero/oracle.go:432). Here the coordinator is a small passive object the
engine calls synchronously; the cluster layer wraps it in a DCN service
and Raft once multi-host lands. The conflict-detection semantics are
identical: a txn T aborts iff some key it wrote was committed by another
txn with commitTs > T.startTs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class TxnAborted(Exception):
    """Transaction aborted due to conflict (ref x.ErrConflict /
    pb.TxnContext.Aborted)."""


class StaleSnapshot(TxnAborted):
    """A pinned read's timestamp fell below a tablet's rollup
    watermark: commits newer than the read ts were already folded into
    base state, so the exact snapshot no longer exists.  Retryable —
    re-issue the read at a fresh timestamp (subclassing TxnAborted
    rides the existing retry/ABORTED mappings on every transport)."""


@dataclass
class TxnState:
    start_ts: int
    conflict_keys: set = field(default_factory=set)
    committed: bool = False
    aborted: bool = False


class Coordinator:
    def __init__(self):
        self._lock = threading.Lock()
        self._ts = 0              # last issued timestamp
        self._next_uid = 1
        # conflict window: key fingerprint -> last commit_ts
        self._commits: dict[int, int] = {}
        self._active: dict[int, TxnState] = {}
        self._min_active: int = 0
        # pinned snapshot reads: ts -> [refcount, monotonic expiry].
        # Holds the rollup watermark at/below the ts of any in-flight
        # read — folding a commit ABOVE a reader's ts fuses it into
        # base state the reader cannot exclude (the split-bank
        # invariant broke exactly this way). The TTL reaps pins leaked
        # by a crashed reader.
        self._pinned: dict[int, list] = {}
        # tablet map: predicate -> group id (single group 1 in round 1)
        self.tablets: dict[str, int] = {}
        self.groups: set[int] = {1}

    # -- timestamps (ref zero/assign.go:64) --

    # when set, timestamps come from the cluster's Zero quorum (one
    # allocation RPC each, like the reference's zero AssignTimestampIds)
    # so every group's ts live in ONE global order and cross-group
    # snapshot reads are comparable. fn(n) -> first ts of a block of n.
    ts_source_fn = None

    def _alloc_ts(self) -> int:
        if self.ts_source_fn is not None:
            ts = self.ts_source_fn(1)
            self._ts = max(self._ts, ts)
            return ts
        self._ts += 1
        return self._ts

    def next_ts(self) -> int:
        with self._lock:
            return self._alloc_ts()

    def max_assigned(self) -> int:
        with self._lock:
            return self._ts

    def observe_ts(self, ts: int):
        """Advance the local high-water mark past a ts somebody else
        allocated (replay/replication) WITHOUT allocating — with a zero
        ts source, allocation is an RPC and must never run in a
        catch-up loop."""
        with self._lock:
            self._ts = max(self._ts, ts)

    # -- uid leases (ref zero/assign.go:158) --

    # when set, uid blocks come from the cluster's Zero quorum instead
    # of the local counter, so every group allocates from ONE disjoint
    # space (without this, two groups both start at uid 1 and a tablet
    # move would merge unrelated entities). fn(n) -> first uid.
    uid_lease_fn = None
    UID_LEASE_BLOCK = 10_000

    def assign_uids(self, n: int) -> tuple[int, int]:
        """Lease [first, last] inclusive."""
        with self._lock:
            if self.uid_lease_fn is not None:
                end = getattr(self, "_lease_end", 0)
                if self._next_uid + n - 1 > end:
                    block = max(n, self.UID_LEASE_BLOCK)
                    first = self.uid_lease_fn(block)
                    self._next_uid = first
                    self._lease_end = first + block - 1
            first = self._next_uid
            self._next_uid += n
            return first, self._next_uid - 1

    def bump_uids(self, to: int):
        with self._lock:
            self._next_uid = max(self._next_uid, to + 1)

    # -- transactions (ref zero/oracle.go) --

    def begin(self) -> TxnState:
        with self._lock:
            st = TxnState(start_ts=self._alloc_ts())
            self._active[st.start_ts] = st
            return st

    def begin_at(self, start_ts: int) -> TxnState:
        """Register a txn at a previously issued read timestamp — the
        stateless-HTTP flow where a query hands out startTs and a later
        /mutate attaches to it (ref posting.Oracle RegisterStartTs)."""
        with self._lock:
            if start_ts <= 0 or start_ts > self._ts:
                raise ValueError(f"unknown startTs {start_ts}")
            if start_ts in self._active:
                raise ValueError(f"startTs {start_ts} already in use")
            st = TxnState(start_ts=start_ts)
            self._active[start_ts] = st
            return st

    # when set, commit decisions come from the cluster's Zero quorum
    # (fn(start_ts, sorted_keys) -> commit_ts, 0 = conflict abort) so
    # EVERY group's transactions share one global conflict oracle —
    # exactly the reference, where all commits flow through Zero
    # (zero/oracle.go:326). The decision is mirrored into the local
    # window so replica-side checks stay consistent.
    commit_source_fn = None

    def commit(self, txn: TxnState, conflict_keys: set) -> int:
        """Conflict-check and commit; returns commit_ts.
        Raises TxnAborted on conflict (ref zero/oracle.go:326 s.commit)."""
        with self._lock:
            st = self._active.get(txn.start_ts)
            if st is None or st.aborted:
                raise TxnAborted(f"txn {txn.start_ts} not active")
            if self.commit_source_fn is not None:
                commit_ts = self.commit_source_fn(
                    txn.start_ts, sorted(int(k) for k in conflict_keys))
                del self._active[txn.start_ts]
                if not commit_ts:
                    st.aborted = True
                    raise TxnAborted(
                        f"zero oracle aborted txn {txn.start_ts} "
                        "(write-write conflict)")
                self._ts = max(self._ts, commit_ts)
                for key in conflict_keys:
                    if commit_ts > self._commits.get(key, 0):
                        self._commits[key] = commit_ts
                st.committed = True
                return commit_ts
            for key in conflict_keys:
                last = self._commits.get(key, 0)
                if last > txn.start_ts:
                    st.aborted = True
                    del self._active[txn.start_ts]
                    raise TxnAborted(
                        f"conflict on key {key:#x}: committed at {last} > "
                        f"start {txn.start_ts}")
            commit_ts = self._alloc_ts()
            for key in conflict_keys:
                self._commits[key] = commit_ts
            st.committed = True
            del self._active[txn.start_ts]
            return commit_ts

    def register_commit(self, conflict_keys: set, commit_ts: int):
        """Mirror an externally decided commit into the conflict window
        (ref posting/oracle.go:207 ProcessDelta: every alpha replays
        Zero's commit decisions into its local oracle). Used by the
        Raft apply path so a deposed-then-re-elected leader's conflict
        checks see writes that committed through another leader."""
        with self._lock:
            self._ts = max(self._ts, commit_ts)
            for key in conflict_keys:
                if commit_ts > self._commits.get(key, 0):
                    self._commits[key] = commit_ts

    def abort(self, txn: TxnState):
        with self._lock:
            st = self._active.pop(txn.start_ts, None)
            if st:
                st.aborted = True

    def pin_read(self, ts: int, ttl_s: float = 60.0):
        """Register an in-flight pinned snapshot read at `ts` (see
        _pinned). Always pair with unpin_read."""
        with self._lock:
            ent = self._pinned.get(ts)
            exp = time.monotonic() + ttl_s
            if ent is not None:
                ent[0] += 1
                ent[1] = max(ent[1], exp)
            else:
                self._pinned[ts] = [1, exp]

    def unpin_read(self, ts: int):
        with self._lock:
            ent = self._pinned.get(ts)
            if ent is not None:
                ent[0] -= 1
                if ent[0] <= 0:
                    del self._pinned[ts]

    def min_active_ts(self) -> int:
        """Rollup watermark: everything <= this is safe to fold
        (ref worker/draft.go:1206 calculateSnapshot picking a ReadTs
        below all pending txns). Pinned snapshot reads hold it too —
        folding UP TO a pinned ts is safe (the reader sees base +
        overlay <= its ts), past it is not."""
        with self._lock:
            wm = min(self._active) - 1 if self._active else self._ts
            if self._pinned:
                now = time.monotonic()
                dead = [t for t, ent in self._pinned.items()
                        if ent[1] < now]
                for t in dead:
                    del self._pinned[t]
                if self._pinned:
                    wm = min(wm, min(self._pinned))
            return wm

    def gc_conflicts(self):
        """Drop conflict entries older than every active txn."""
        with self._lock:
            floor = min(self._active) if self._active else self._ts
            self._commits = {k: v for k, v in self._commits.items()
                             if v >= floor}

    # -- tablet ownership (ref zero/zero.go:564 ShouldServe) --

    def should_serve(self, pred: str, group: int = 1) -> int:
        with self._lock:
            gid = self.tablets.get(pred)
            if gid is None:
                gid = group
                self.tablets[pred] = gid
            return gid
