"""Deterministic in-process Raft cluster harness.

The reference tests multi-node behavior with docker-compose topologies
plus Jepsen nemeses (SURVEY §4.5, §4.7: partition-ring, kill-alpha,
clock skew). Our equivalent is a simulated network: every node is a
tick-driven RaftNode, messages flow through a bus with per-link drop /
partition controls, and the scheduler pumps ticks deterministically —
the same failure scenarios run in milliseconds with a seeded RNG, no
containers.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Optional

from dgraph_tpu.cluster.raft import LEADER, Msg, RaftNode


class SimCluster:
    """N Raft nodes over a lossy, partitionable in-memory network."""

    def __init__(self, n: int, storage_factory: Optional[Callable] = None,
                 seed: int = 0, election_ticks: int = 10):
        self.ids = list(range(1, n + 1))
        self.rng = random.Random(seed)
        self.election_ticks = election_ticks
        self.storage_factory = storage_factory or (lambda node_id: None)
        self.nodes: dict[int, RaftNode] = {}
        self.applied: dict[int, list] = {i: [] for i in self.ids}
        self.inbox: list[Msg] = []
        self.cut: set[tuple[int, int]] = set()   # directed broken links
        self.down: set[int] = set()
        self.drop_rate = 0.0
        self.on_apply: Optional[Callable[[int, Any], None]] = None
        self.on_restore: Optional[Callable[[int, Any], None]] = None
        for i in self.ids:
            self._start(i)

    def _start(self, i: int):
        self.nodes[i] = RaftNode(
            i, self.ids, storage=self.storage_factory(i),
            election_ticks=self.election_ticks,
            rng=random.Random(self.rng.randrange(1 << 30)))

    # ----------------------------------------------------------- failures

    def partition(self, side_a: list[int], side_b: list[int]):
        for a in side_a:
            for b in side_b:
                self.cut.add((a, b))
                self.cut.add((b, a))

    def heal(self):
        self.cut.clear()

    def kill(self, i: int):
        self.down.add(i)
        self.inbox = [m for m in self.inbox if m.to != i and m.frm != i]

    def restart(self, i: int):
        """Node comes back from its persistent storage only."""
        self.down.discard(i)
        self._start(i)
        r = self.nodes[i].ready()
        if r.snapshot is not None and self.on_restore:
            self.on_restore(i, r.snapshot[2])

    # ------------------------------------------------------------ pumping

    def pump(self, ticks: int = 1):
        for _ in range(ticks):
            for i in self.ids:
                if i in self.down:
                    continue
                self.nodes[i].tick()
            self._drain()

    def _drain(self, rounds: int = 20):
        for _ in range(rounds):
            if not self.inbox:
                progressed = False
            else:
                progressed = True
                batch, self.inbox = self.inbox, []
                for m in batch:
                    if (m.frm, m.to) in self.cut or m.to in self.down \
                            or m.frm in self.down:
                        continue
                    if self.drop_rate and \
                            self.rng.random() < self.drop_rate:
                        continue
                    self.nodes[m.to].step(m)
            for i in self.ids:
                if i in self.down:
                    continue
                r = self.nodes[i].ready()
                self.inbox.extend(r.msgs)
                if r.snapshot is not None and self.on_restore:
                    self.on_restore(i, r.snapshot[2])
                for e in r.committed:
                    if e.data is not None:
                        self.applied[i].append(e.data)
                        if self.on_apply:
                            self.on_apply(i, e.data)
            if not progressed and not self.inbox:
                return

    # ------------------------------------------------------------- helpers

    def leader(self) -> Optional[int]:
        for i in self.ids:
            if i not in self.down and self.nodes[i].role == LEADER:
                return i
        return None

    def wait_leader(self, max_ticks: int = 200) -> int:
        for _ in range(max_ticks):
            lead = self.leader()
            if lead is not None:
                return lead
            self.pump()
        raise AssertionError("no leader elected")

    def propose(self, data: Any, retries: int = 50) -> bool:
        for _ in range(retries):
            lead = self.leader()
            if lead is not None and self.nodes[lead].propose(data):
                self._drain()
                return True
            self.pump()
        return False
