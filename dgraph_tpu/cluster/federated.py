"""Federated query execution: one executor, tablets on many groups.

The reference executes every query as a task tree where each attr's
fetch routes to the group serving that attr (worker/task.go:131
ProcessTaskOverNetwork -> groups.go:378 BelongsTo). This module is that
capability for queries the block-wise scatter cannot serve: a SINGLE
block whose predicates live on different groups, or variables flowing
between blocks on different groups.

Design: the full (unchanged) query executor runs in the coordinating
process over a FederatedDB whose tablets are RemoteTablet proxies. A
proxy answers the Tablet read surface by batched "task" RPCs to the
predicate's owning group at one zero-issued global read_ts, caching
per query. Hot per-uid loops in the executor prefetch whole uid
batches (prefetch_edges / prefetch_postings), so one block level costs
one RPC per predicate — the same fan-out unit as the reference's
per-attr task messages.

Consistency: the read_ts is allocated by zero AFTER every commit it
must see; each group's first task pays a quorum read barrier
(leader-only + no-op round trip) and reconciles decided-but-unapplied
cross-group commits <= read_ts before answering.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dgraph_tpu.engine.db import GraphDB
from dgraph_tpu.models.types import TypeID

_EMPTY = np.empty(0, dtype=np.uint64)


class RemoteTablet:
    """Tablet read-surface proxy over the owning group's task RPCs.
    Mirrors storage/tablet.py's read methods; caches per instance
    (instances live for one query, so caches are snapshot-consistent
    at read_ts)."""

    def __init__(self, fdb: "FederatedDB", pred: str, gid: int, schema,
                 expect_whole: bool = True):
        self._fdb = fdb
        self._gid = gid
        self.pred = pred
        self.schema = schema
        # True = this proxy believes `gid` serves the WHOLE predicate
        # (rides every task as `whole`): a group holding only a hash
        # range rejects such tasks typed, so a coordinator whose map
        # predates a split flip re-routes instead of silently reading
        # partial rows. SplitRemoteTablet's sub-proxies set False.
        self.expect_whole = expect_whole
        self._postings: dict[int, list] = {}
        self._edges: dict[tuple[int, bool], np.ndarray] = {}
        self._index: dict[bytes, np.ndarray] = {}
        self._counts: dict[tuple[int, bool], int] = {}
        self._facets: dict[tuple[int, int], dict] = {}
        self._src_uids: Optional[np.ndarray] = None
        self._dst_uids: Optional[np.ndarray] = None
        self._count_table = None
        self._sort_pairs = None

    # ------------------------------------------------------------- rpc

    def _task(self, kind: str, **args):
        return self._fdb._task(self._gid, dict(
            args, op="task", kind=kind, pred=self.pred,
            whole=self.expect_whole, read_ts=self._fdb.read_ts))

    @staticmethod
    def _u64(a) -> np.ndarray:
        return np.asarray(a, dtype=np.uint64)

    # ------------------------------------------------------- prefetch

    def prefetch_edges(self, uids, reverse: bool = False):
        miss = [int(u) for u in np.asarray(uids).tolist()
                if (int(u), reverse) not in self._edges]
        if not miss:
            return
        got = self._task("edges", uids=np.asarray(miss, np.uint64),
                         reverse=reverse)
        if got is None:  # tablet absent on its group: negative-cache
            got = [_EMPTY] * len(miss)
        for u, dsts in zip(miss, got):
            self._edges[(u, reverse)] = self._u64(dsts)

    def prefetch_postings(self, uids):
        miss = [int(u) for u in np.asarray(uids).tolist()
                if int(u) not in self._postings]
        if not miss:
            return
        got = self._task("postings",
                         uids=np.asarray(miss, np.uint64))
        if got is None:
            got = [[]] * len(miss)
        for u, ps in zip(miss, got):
            self._postings[u] = list(ps)

    def prefetch_counts(self, uids, reverse: bool = False):
        """Batch the per-uid fan-out counts for one block level into a
        single task RPC (ref worker/task.go per-attr task granularity;
        round-3 verdict: count(pred) over k uids paid k round trips)."""
        miss = [int(u) for u in np.asarray(uids).tolist()
                if (int(u), reverse) not in self._counts
                and not self._count_from_edges(int(u), reverse)]
        if not miss:
            return
        got = self._task("counts", uids=np.asarray(miss, np.uint64),
                         reverse=reverse)
        if got is None:
            got = [0] * len(miss)
        for u, c in zip(miss, got):
            self._counts[(u, reverse)] = int(c)

    def prefetch_facets(self, pairs):
        """Batch facet reads for a level's (src, dst) edge pairs into
        one task RPC."""
        miss = [(int(s), int(d)) for s, d in pairs
                if (int(s), int(d)) not in self._facets]
        if not miss:
            return
        got = self._task("facets", pairs=miss)
        if got is None:
            got = [{}] * len(miss)
        for key, fc in zip(miss, got):
            self._facets[key] = dict(fc)

    # ------------------------------------------------- tablet surface

    def get_dst_uids(self, src: int, read_ts: int) -> np.ndarray:
        key = (int(src), False)
        if key not in self._edges:
            self.prefetch_edges([src], reverse=False)
        return self._edges.get(key, _EMPTY)

    def get_reverse_uids(self, dst: int, read_ts: int) -> np.ndarray:
        key = (int(dst), True)
        if key not in self._edges:
            self.prefetch_edges([dst], reverse=True)
        return self._edges.get(key, _EMPTY)

    def get_postings(self, src: int, read_ts: int) -> list:
        if int(src) not in self._postings:
            self.prefetch_postings([src])
        return self._postings.get(int(src), [])

    def expand_frontier(self, frontier: np.ndarray, read_ts: int,
                        reverse: bool = False) -> np.ndarray:
        got = self._task("expand", uids=self._u64(frontier),
                         reverse=bool(reverse))
        return self._u64(got if got is not None else _EMPTY)

    def src_uids(self, read_ts: int) -> np.ndarray:
        if self._src_uids is None:
            got = self._task("src_uids")
            self._src_uids = self._u64(got) if got is not None \
                else _EMPTY.copy()
        return self._src_uids

    def dst_uids(self, read_ts: int) -> np.ndarray:
        if self._dst_uids is None:
            got = self._task("dst_uids")
            self._dst_uids = self._u64(got) if got is not None \
                else _EMPTY.copy()
        return self._dst_uids

    def index_uids(self, token: bytes, read_ts: int) -> np.ndarray:
        tok = bytes(token)
        if tok not in self._index:
            got = self._task("index", tokens=[tok])
            self._index[tok] = self._u64(got[0]) if got is not None \
                else _EMPTY.copy()
        return self._index[tok]

    def count_of(self, src: int, read_ts: int,
                 reverse: bool = False) -> int:
        return self._count(int(src), reverse=reverse)

    def _count_from_edges(self, uid: int, reverse: bool) -> bool:
        """Derive a UID-predicate count from an already-prefetched edge
        list instead of re-asking the group (the level's edges were
        shipped for expansion anyway; scalar tablets never enter the
        edge cache, so a hit here is always count-exact)."""
        dsts = self._edges.get((uid, reverse))
        if dsts is None or not self.schema.value_type == TypeID.UID:
            return False
        self._counts[(uid, reverse)] = len(dsts)
        return True

    def _count(self, uid: int, reverse: bool) -> int:
        key = (uid, reverse)
        if key not in self._counts and \
                not self._count_from_edges(uid, reverse):
            got = self._task("counts",
                             uids=np.asarray([uid], np.uint64),
                             reverse=reverse) or [0]
            self._counts[key] = int(got[0])
        return self._counts[key]

    def count_table(self):
        if self._count_table is None:
            got = self._task("count_table")
            if got is None:
                got = (_EMPTY, np.empty(0, np.int64))
            self._count_table = (self._u64(got[0]),
                                 np.asarray(got[1], np.int64))
        return self._count_table

    def get_facets(self, src: int, dst: int, read_ts: int) -> dict:
        key = (int(src), int(dst))
        if key not in self._facets:
            got = self._task("facets", pairs=[key]) or [{}]
            self._facets[key] = dict(got[0])
        return self._facets[key]

    def sort_key_pairs(self):
        if self._sort_pairs is None:
            got = self._task("sort_key_pairs") or {}
            self._sort_pairs = {int(k): int(v) for k, v in got.items()}
        return self._sort_pairs

    def dirty(self) -> bool:
        # the serving group answers reads through its own MVCC overlay;
        # the proxy never sees raw overlay state
        return False

    def overlay_srcs(self, read_ts: int, reverse: bool = False):
        return ()


class SplitRemoteTablet:
    """Read surface of a hash-range SPLIT predicate: one RemoteTablet
    per owning group, per-uid calls routed by subject hash
    (cluster/shard.py — each row lives on exactly one sub-tablet),
    set-valued calls fanned to every owner and UNIONED (token-index
    probes, src/dst uid sets, reverse lookups: sub-tablets index only
    their own rows, so the union is exact and disjointness makes it
    cheap). This is the piece that lets the unchanged executor run
    over a split predicate as if it were whole."""

    def __init__(self, fdb: "FederatedDB", pred: str,
                 owners: list[int], schema):
        self.pred = pred
        self.schema = schema
        self._owners = [int(g) for g in owners]
        # one proxy per DISTINCT group (a group owning two shards
        # serves both from its single local tablet); sub-proxies
        # EXPECT partial copies (expect_whole=False)
        self._subs = {gid: RemoteTablet(fdb, pred, gid, schema,
                                        expect_whole=False)
                      for gid in sorted(set(self._owners))}

    def _sub_for(self, uid: int) -> RemoteTablet:
        from dgraph_tpu.cluster.shard import shard_of
        return self._subs[
            self._owners[shard_of(int(uid), len(self._owners))]]

    def _route_uids(self, uids) -> dict:
        """Partition a uid batch by owning group — vectorized
        (shard_mask is numpy splitmix64): a viral predicate's
        frontier is exactly where a per-uid Python hash loop would
        dominate the coordinator."""
        from dgraph_tpu.cluster.shard import shard_mask
        arr = np.asarray(uids, np.uint64)
        n = len(self._owners)
        out: dict[int, np.ndarray] = {}
        for shard, gid in enumerate(self._owners):
            part = arr[shard_mask(arr, n, shard)]
            if len(part):
                prev = out.get(gid)
                out[gid] = part if prev is None \
                    else np.concatenate([prev, part])
        return out

    @staticmethod
    def _union(parts: list[np.ndarray]) -> np.ndarray:
        parts = [p for p in parts if len(p)]
        if not parts:
            return _EMPTY
        out = parts[0]
        for p in parts[1:]:
            out = np.union1d(out, p)
        return np.asarray(out, np.uint64)

    def _owned(self, gid: int, uids) -> np.ndarray:
        """Keep only SUBJECT uids whose shard `gid` OWNS per the
        routing map. Every union-shaped read filters each group's
        answer through this: in the flip->prune window the source
        still physically holds the moved range (frozen at the fence
        watermark — post-flip writes land on the destination), so an
        unfiltered union would resurface overwritten values and
        deleted edges from the stale copy. Ownership-filtering makes
        the union exact regardless of prune timing."""
        from dgraph_tpu.cluster.shard import shard_mask
        arr = np.asarray(uids, np.uint64)
        if not len(arr):
            return arr
        n = len(self._owners)
        keep = np.zeros(len(arr), bool)
        for shard, g in enumerate(self._owners):
            if g == gid:
                keep |= shard_mask(arr, n, shard)
        return arr[keep]

    # ------------------------------------------------- prefetch (by uid)

    def prefetch_edges(self, uids, reverse: bool = False):
        if reverse:
            return  # reverse lookups fan out per call (see below)
        for gid, us in self._route_uids(uids).items():
            self._subs[gid].prefetch_edges(us, reverse=False)

    def prefetch_postings(self, uids):
        for gid, us in self._route_uids(uids).items():
            self._subs[gid].prefetch_postings(us)

    def prefetch_counts(self, uids, reverse: bool = False):
        if reverse:
            return
        for gid, us in self._route_uids(uids).items():
            self._subs[gid].prefetch_counts(us, reverse=False)

    def prefetch_facets(self, pairs):
        by: dict[int, list] = {}
        from dgraph_tpu.cluster.shard import shard_of
        for s, d in pairs:
            gid = self._owners[shard_of(int(s), len(self._owners))]
            by.setdefault(gid, []).append((int(s), int(d)))
        for gid, ps in by.items():
            self._subs[gid].prefetch_facets(ps)

    # ------------------------------------------------- tablet surface

    def get_dst_uids(self, src: int, read_ts: int) -> np.ndarray:
        return self._sub_for(src).get_dst_uids(src, read_ts)

    def get_reverse_uids(self, dst: int, read_ts: int) -> np.ndarray:
        # the edges POINTING AT dst may originate in any shard: fan
        # out and union, each group's answer filtered to the SUBJECT
        # shards it owns
        return self._union(
            [self._owned(g, t.get_reverse_uids(dst, read_ts))
             for g, t in self._subs.items()])

    def get_postings(self, src: int, read_ts: int) -> list:
        return self._sub_for(src).get_postings(src, read_ts)

    def expand_frontier(self, frontier: np.ndarray, read_ts: int,
                        reverse: bool = False) -> np.ndarray:
        if reverse:
            # reverse expansion returns SUBJECT uids: filter each
            # group's answer to its owned shards before the union
            return self._union(
                [self._owned(g, t.expand_frontier(frontier, read_ts,
                                                  True))
                 for g, t in self._subs.items()])
        parts = []
        for gid, us in self._route_uids(frontier).items():
            parts.append(self._subs[gid].expand_frontier(
                np.asarray(us, np.uint64), read_ts, False))
        return self._union(parts)

    def src_uids(self, read_ts: int) -> np.ndarray:
        return self._union([self._owned(g, t.src_uids(read_ts))
                            for g, t in self._subs.items()])

    def dst_uids(self, read_ts: int) -> np.ndarray:
        # OBJECT uids are not shard-partitioned, so ownership cannot
        # filter here; the union dedupes, and the residual exposure
        # (a dst whose last in-edge was deleted post-flip lingering
        # until the source prunes) is bounded by the prune delivery
        return self._union([t.dst_uids(read_ts)
                            for t in self._subs.values()])

    def index_uids(self, token: bytes, read_ts: int) -> np.ndarray:
        return self._union(
            [self._owned(g, t.index_uids(token, read_ts))
             for g, t in self._subs.items()])

    def count_of(self, src: int, read_ts: int,
                 reverse: bool = False) -> int:
        if reverse:
            # count the UNION, not the sum of counts: in the short
            # flip->prune window both groups still hold the moved
            # range's rows and a raw sum would double-count
            return len(self.get_reverse_uids(src, read_ts))
        return self._sub_for(src).count_of(src, read_ts)

    def count_table(self):
        srcs, cnts = [], []
        for g, t in self._subs.items():
            s, c = t.count_table()
            s = np.asarray(s, np.uint64)
            # ownership-filter each group's rows (see _owned): the
            # unpruned source's moved-range rows are stale the moment
            # a post-flip write lands on the destination
            keep = np.isin(s, self._owned(g, s))
            srcs.append(s[keep])
            cnts.append(np.asarray(c, np.int64)[keep])
        s = np.concatenate(srcs) if srcs else _EMPTY
        c = np.concatenate(cnts) if cnts else np.empty(0, np.int64)
        order = np.argsort(s, kind="stable")  # disjoint by ownership
        return s[order], c[order]

    def sort_key_pairs(self):
        out: dict[int, int] = {}
        for g, t in self._subs.items():
            pairs = t.sort_key_pairs()
            owned = set(self._owned(
                g, np.fromiter(pairs, np.uint64,
                               len(pairs))).tolist())
            out.update((u, v) for u, v in pairs.items()
                       if int(u) in owned)
        return out

    def get_facets(self, src: int, dst: int, read_ts: int) -> dict:
        return self._sub_for(src).get_facets(src, dst, read_ts)

    def dirty(self) -> bool:
        return False

    def overlay_srcs(self, read_ts: int, reverse: bool = False):
        return ()


class _RemoteTablets(dict):
    """Lazy pred -> RemoteTablet mapping over the cluster tablet map
    (+ SplitRemoteTablet fan-outs for hash-range split predicates)."""

    def __init__(self, fdb: "FederatedDB", tmap: dict[str, int],
                 splits: Optional[dict] = None):
        super().__init__()
        self._fdb = fdb
        self._tmap = dict(tmap)
        self._splits = dict(splits or {})

    def get(self, pred, default=None):
        tab = dict.get(self, pred)
        if tab is not None:
            return tab
        ent = self._splits.get(pred)
        if ent is not None:
            tab = SplitRemoteTablet(
                self._fdb, pred, ent["owners"],
                self._fdb.schema.get_or_default(pred))
            self[pred] = tab
            return tab
        gid = self._tmap.get(pred)
        if gid is None:
            return default
        tab = RemoteTablet(self._fdb, pred, gid,
                           self._fdb.schema.get_or_default(pred))
        self[pred] = tab
        return tab

    def __contains__(self, pred):
        return dict.__contains__(self, pred) or pred in self._tmap \
            or pred in self._splits


class FederatedDB(GraphDB):
    """GraphDB whose tablets live on remote groups. query() is the
    inherited engine path (parse -> Executor -> emission) — only the
    tablet fetches go remote, exactly the reference's split between
    query planning and per-attr worker tasks."""

    def __init__(self, groups: dict[int, object], tmap: dict[str, int],
                 schema_text: str, read_ts: int, ctx=None,
                 splits: Optional[dict] = None):
        super().__init__(prefer_device=False)
        self._groups = groups
        self.read_ts = read_ts
        # coordinator-side RequestContext: every task RPC checks it
        # and ships the REMAINING budget as deadline_ms so the owning
        # group inherits the deadline (plus a small skew allowance on
        # its side) — the reference forwards its context on every
        # worker RPC (worker/task.go ProcessTaskOverNetwork)
        self.req_ctx = ctx
        if schema_text:
            self.schema.apply_text(schema_text)
        self.tablets = _RemoteTablets(self, tmap, splits=splits)

    def _task(self, gid: int, req: dict):
        # the serving node pays the quorum read barrier on every task
        # (a cached client-side barrier would go stale on a mid-query
        # leader change), so there is nothing to track here
        deadline_s = None
        if self.req_ctx is not None:
            self.req_ctx.check(f"task on group {gid}")
            rem = self.req_ctx.remaining_ms()
            if rem is not None:
                req = dict(req, deadline_ms=rem,
                           trace_id=self.req_ctx.trace_id)
                # the budget also bounds the CLIENT-side wait: an
                # election on the owning group must not hold an
                # expired coordinator for the full default timeout
                deadline_s = rem / 1000.0
        cl = self._groups[gid]
        resp = cl.request(req, deadline_s=deadline_s)
        if not resp.get("ok"):
            if self.req_ctx is not None:
                # a budget that ran out DURING the RPC must surface as
                # DeadlineExceeded (-> 408, retryable), not as a
                # generic task failure (-> 500)
                self.req_ctx.check(f"task on group {gid}")
            if resp.get("misrouted"):
                # the tablet flipped away mid-query: typed, so the
                # router re-fetches its map and re-runs the query
                from dgraph_tpu.cluster.errors import TabletMisrouted
                m = resp["misrouted"]
                raise TabletMisrouted(m.get("pred", "?"),
                                      m.get("group"),
                                      resp.get("error", ""))
            raise RuntimeError(
                f"task {req.get('kind')} on group {gid} failed: "
                f"{resp.get('error')}")
        return resp["result"]

    def query(self, q: str, variables: dict | None = None, **kw):
        kw.setdefault("read_ts", self.read_ts)
        kw.setdefault("ctx", self.req_ctx)
        return super().query(q, variables, **kw)
