from dgraph_tpu.cli import main

raise SystemExit(main())
