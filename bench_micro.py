"""Microbench: UID-set intersect bandwidth (BASELINE.json's second
metric, "UID-intersect GB/s").

Mirrors the reference's harness shape (algo/uidlist_test.go:290
BenchmarkListIntersect*: two sorted lists, size ratio + overlap sweep)
on the device kernels (ops/uidvec.intersect — vectorized searchsorted
membership). The CPU baseline is np.intersect1d on the same data.

The driver-facing benchmark stays bench.py (one JSON line); this is
the supplementary micro harness. Prints one JSON line per config and a
summary line.
"""

import json
import os
import sys
import time

import numpy as np

RUNS = 9


def make_pair(n_a: int, ratio: int, overlap: float, seed: int = 0):
    """Two sorted unique uint32 lists; |b| = n_a * ratio; ~overlap of
    a's elements also appear in b (the reference's sweep axes)."""
    rng = np.random.default_rng(seed)
    n_b = n_a * ratio
    space = np.uint32(4_000_000_000)
    b = np.unique(rng.integers(0, space, n_b, dtype=np.uint32))
    take = rng.random(len(b)) < (overlap * n_a / max(len(b), 1))
    shared = b[take][:n_a]
    fresh = np.unique(rng.integers(0, space, n_a, dtype=np.uint32))
    a = np.unique(np.concatenate([shared, fresh]))[:n_a]
    return a, b


def kway_bench():
    """k-way vs pairwise host set algebra (ops/setops): the executor's
    old fold was k-1 union1d accumulator re-sorts / a size-blind
    intersect fold; union_many is concat + ONE sort, intersect_many is
    smallest-first galloping. Sweeps k = 8 / 64 / 512 sets so the
    setops win is tracked independently of the query suite."""
    from functools import reduce

    from dgraph_tpu.ops import setops

    rng = np.random.default_rng(7)
    out = []
    for k, n in [(8, 65_536), (64, 8_192), (512, 1_024)]:
        space = 4 * k * n
        sets = [np.unique(rng.integers(0, space, n).astype(np.uint64))
                for _ in range(k)]
        # one shared run so intersections are non-empty
        shared = np.unique(
            rng.integers(0, space, n // 4).astype(np.uint64))
        isets = [np.unique(np.concatenate([s[: n // 2], shared]))
                 for s in sets]

        def timed(fn, runs=5):
            best = float("inf")
            for _ in range(runs):
                t = time.perf_counter()
                got = fn()
                best = min(best, time.perf_counter() - t)
            return best, got

        pu_t, pu = timed(lambda: reduce(np.union1d, sets))
        ku_t, ku = timed(lambda: setops.union_many(sets))
        assert np.array_equal(pu, ku)
        pi_t, pi = timed(lambda: reduce(
            lambda a, b: np.intersect1d(a, b, assume_unique=True),
            isets))
        ki_t, ki = timed(lambda: setops.intersect_many(isets))
        assert np.array_equal(pi, ki)
        rec = {"metric": "setops_kway", "sets": k, "set_size": n,
               "union_pairwise_ms": round(pu_t * 1e3, 2),
               "union_kway_ms": round(ku_t * 1e3, 2),
               "union_speedup": round(pu_t / max(ku_t, 1e-9), 2),
               "intersect_pairwise_ms": round(pi_t * 1e3, 2),
               "intersect_kway_ms": round(ki_t * 1e3, 2),
               "intersect_speedup": round(pi_t / max(ki_t, 1e-9), 2)}
        out.append(rec)
        print(json.dumps(rec))
    best = max(r["union_speedup"] for r in out)
    print(json.dumps({"metric": "setops_kway_union_speedup",
                      "value": best, "unit": "x"}))


def setops_compressed_bench(runs: int = 5) -> dict:
    """`--setops-compressed`: compressed-vs-dense set algebra sweep
    (ops/codec.CompressedPack + ops/setops pack kernels).

    Axes: block-form mix (array/packed, bitmap, run) x three densities
    x selectivity (how many posting blocks actually overlap). For each
    config three arms are timed:

      dense       intersect_many over the already-dense uid vectors
                  (the old tier's steady state: dense CSR resident)
      decode+i    densify every pack, then intersect_many — what a
                  compressed-at-rest store WITHOUT compressed set
                  algebra would pay per query
      compressed  intersect_packs: descriptor skipping + bitmap word
                  AND + mixed-form probes, decoding survivors only

    The GATE (tools/check.sh): on the selective-intersection config,
    `compressed` must beat `decode+i` — block skipping is the whole
    point; losing it means the kernels regressed into decode-always.
    Also prints the resident-bytes ratio per mix (the >= 3x at-rest
    claim's microscale witness) and a compressed-vs-dense crossover
    table. Budget override: DGRAPH_TPU_SETOPS_BUDGET (ratio,
    default 1.0 = must simply win)."""
    from dgraph_tpu.ops import codec, setops

    budget = float(os.environ.get("DGRAPH_TPU_SETOPS_BUDGET", "1.0"))
    rng = np.random.default_rng(20260803)
    scratch = codec.DecodeScratch()

    def mk(mix: str, n: int, span: int, base: int = 0):
        if mix == "run":
            starts = np.unique(rng.integers(
                0, span, max(n // 64, 1), dtype=np.uint64))
            s = np.unique(np.concatenate(
                [np.arange(st, st + 64, dtype=np.uint64)
                 for st in starts]))[:n]
        elif mix == "bitmap":
            # dense inside few blocks
            s = np.unique(rng.integers(
                0, max(n * 3 // 2, 1), n, dtype=np.uint64))
        else:  # array/packed: sparse over the whole span
            s = np.unique(rng.integers(0, span, n, dtype=np.uint64))
        return s + np.uint64(base)

    def timed(fn):
        best = float("inf")
        got = None
        for _ in range(runs):
            t0 = time.perf_counter()
            got = fn()
            best = min(best, time.perf_counter() - t0)
        return best, got

    out = []
    # (mix, n per set, uid span) — three densities per form family
    configs = [
        ("array", 20_000, 1 << 34),   # sparse: packed blocks
        ("array", 200_000, 1 << 26),  # mid density
        ("bitmap", 200_000, 1 << 19),  # dense: bitmap blocks
        ("run", 100_000, 1 << 24),    # runny
    ]
    for mix, n, span in configs:
        shared = mk(mix, n // 4, span)
        sets = [np.unique(np.concatenate([mk(mix, n, span), shared]))
                for _ in range(4)]
        packs = [codec.compress(s) for s in sets]
        d_t, want = timed(lambda: setops.intersect_many(sets))
        dd_t, got_d = timed(lambda: setops.intersect_many(
            [p.densify() for p in packs]))
        c_t, got = timed(lambda: setops.intersect_packs(
            packs, scratch=scratch))
        assert np.array_equal(want, got) \
            and np.array_equal(want, got_d), mix
        u_t, uw = timed(lambda: setops.union_many(sets))
        cu_t, ug = timed(lambda: setops.union_packs(
            packs, scratch=scratch))
        assert np.array_equal(uw, ug), mix
        dense_b = sum(s.nbytes for s in sets)
        comp_b = sum(p.nbytes for p in packs)
        rec = {"metric": "setops_compressed", "mix": mix,
               "set_size": n, "span_bits": span.bit_length() - 1,
               "dense_intersect_ms": round(d_t * 1e3, 3),
               "decode_then_intersect_ms": round(dd_t * 1e3, 3),
               "compressed_intersect_ms": round(c_t * 1e3, 3),
               "dense_union_ms": round(u_t * 1e3, 3),
               "compressed_union_ms": round(cu_t * 1e3, 3),
               "bytes_dense": dense_b, "bytes_compressed": comp_b,
               "bytes_ratio": round(dense_b / max(comp_b, 1), 2),
               "vs_dense": round(d_t / max(c_t, 1e-9), 2),
               "vs_decode": round(dd_t / max(c_t, 1e-9), 2)}
        out.append(rec)
        print(json.dumps(rec))

    # the GATE config: selective intersection — a small probe set
    # against a huge posting list, almost no block overlap (the
    # reference's IntersectWith lin/bin regime; block skipping must
    # beat decoding the 2M-uid list)
    big = mk("array", 2_000_000, 1 << 36)
    probe = np.unique(np.concatenate(
        [mk("array", 2_000, 1 << 36), big[:: len(big) // 500]]))
    bigp, probep = codec.compress(big), codec.compress(probe)
    want = setops.intersect_many([probe, big])
    dd_t, _ = timed(lambda: setops.intersect_many(
        [probep.densify(), bigp.densify()]))
    c_t, got = timed(lambda: setops.intersect_packs(
        [probep, bigp], scratch=scratch))
    assert np.array_equal(want, got)
    ratio = dd_t / max(c_t, 1e-9)
    gate = {"metric": "setops_compressed_selective",
            "probe": len(probe), "list": len(big),
            "decode_then_intersect_ms": round(dd_t * 1e3, 3),
            "compressed_intersect_ms": round(c_t * 1e3, 3),
            "block_skip_speedup": round(ratio, 2),
            "budget_ratio": budget,
            "within_budget": ratio > budget}
    print(json.dumps(gate))
    return gate


def lint_timing_bench(runs: int = 3):
    """`--lint-timing`: dglint wall time, BOTH modes. Full tree
    (parse + per-file rules + the whole-program call-graph rules,
    dgraph_tpu/ + tests/) must stay < 5 s so the gate stays viable as
    a pre-commit / tier-1 CI hook; a warm `--changed-only` pass
    (summaries served from the content-hash manifest, whole-program
    rules still over every file) must stay < 1 s so `tools/check.sh`
    re-lints per save, not per coffee. One JSON line, microbench
    shape; non-zero exit when either budget is blown."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import tempfile

    from tools.dglint.core import (
        build_project, lint_incremental, lint_project,
    )

    root = os.path.dirname(os.path.abspath(__file__))
    times = []
    n_files = n_findings = 0
    for _ in range(runs):
        t0 = time.monotonic()
        proj = build_project(["dgraph_tpu", "tests"], root)
        findings = lint_project(proj)
        times.append(time.monotonic() - t0)
        n_files, n_findings = len(proj.files), len(findings)
    med = float(np.median(times))

    # incremental: seed a scratch manifest (cold, uncounted), then
    # measure warm passes — the per-save developer loop
    cache = os.path.join(tempfile.mkdtemp(prefix="dglint_bench_"),
                         "cache.json")
    lint_incremental(["dgraph_tpu", "tests"], root, cache)
    inc_times = []
    inc_findings = 0
    for _ in range(runs):
        t0 = time.monotonic()
        inc, _proj, stats = lint_incremental(
            ["dgraph_tpu", "tests"], root, cache)
        inc_times.append(time.monotonic() - t0)
        inc_findings = len(inc)
        assert stats["changed"] == 0, stats  # warm = fully cached
    inc_med = float(np.median(inc_times))

    full_budget = float(os.environ.get("DGRAPH_TPU_LINT_BUDGET",
                                       "5.0"))
    inc_budget = float(os.environ.get("DGRAPH_TPU_LINT_INC_BUDGET",
                                      "1.0"))
    rec = {
        "metric": "dglint_full_tree_s", "value": round(med, 3),
        "unit": "s", "best_s": round(min(times), 3),
        "incremental_s": round(inc_med, 3),
        "incremental_best_s": round(min(inc_times), 3),
        "files": n_files, "findings": n_findings,
        "budget_s": full_budget, "incremental_budget_s": inc_budget,
        "within_budget": med < full_budget and inc_med < inc_budget}
    assert inc_findings == n_findings, \
        (inc_findings, n_findings)  # cached verdicts match the full
    print(json.dumps(rec))
    return rec


def span_overhead_bench(n: int = 20_000, runs: int = 5,
                        budget_us: float = 5.0) -> dict:
    """`--span-overhead`: per-span cost of utils/tracing with
    recording ON vs OFF. The budget is < 5 µs/span — spans sit on the
    executor's per-stage paths, so regressions here show up as a perf
    cliff before any flamegraph would find them. One JSON line in the
    microbench shape; tests/test_tracing.py enforces the budget with
    generous CI slack (shared 1-core runners jitter)."""
    from dgraph_tpu.utils import tracing

    def per_span_us(enabled: bool) -> float:
        tracing.set_enabled(enabled)
        best = float("inf")
        try:
            for _ in range(runs):
                tracing.clear()
                t0 = time.perf_counter_ns()
                for _ in range(n):
                    with tracing.span("bench.span"):
                        pass
                best = min(best,
                           (time.perf_counter_ns() - t0) / n / 1e3)
        finally:
            tracing.set_enabled(True)
        return best

    off = per_span_us(False)
    on = per_span_us(True)
    tracing.clear()
    rec = {"metric": "span_overhead_us",
           "on_us": round(on, 3), "off_us": round(off, 3),
           "recording_cost_us": round(on - off, 3),
           "budget_us": budget_us, "within_budget": on < budget_us}
    print(json.dumps(rec))
    return rec


def _summary_mix():
    """The golden summary-shape queries + the warm GraphDB — ONE
    definition of the 'high-QPS mix' every decomposed overhead gate
    (stats, netfault) times, so the gates can never drift onto
    different mixes."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    from golden import runner

    db = runner.get_db()
    qdir = os.path.join(os.path.dirname(runner.__file__), "queries")
    # the summary shapes: index roots, pagination/sort, counts, term
    # search — the high-QPS mix, not the analytical tail
    names = [n for n in runner.query_names()
             if any(k in n for k in (
                 "eq_root", "allofterms", "anyofterms", "pagination",
                 "count_at_root", "has_edge", "multi_sort"))]
    queries = []
    for n in names:
        with open(os.path.join(qdir, n + ".gql")) as f:
            queries.append(f.read())
    return db, queries


def _mix_pass_us(db, queries) -> float:
    """One timed pass over the summary mix, in µs."""
    t0 = time.perf_counter_ns()
    for q in queries:
        db.query_json(q)
    return (time.perf_counter_ns() - t0) / 1e3


def stats_overhead_bench(runs: int = 5,
                         budget_frac: float = None) -> dict:
    """`--stats-overhead`: cost of the ALWAYS-ON statistics plane (the
    observed-cost span observer, utils/coststore) on the golden
    summary workload — the 21M-regime query shapes at gate scale.

    Methodology: a differential A/B at a sub-1% effect size cannot
    resolve through 1-core CI scheduler noise (±5-10% run to run), so
    the gate decomposes instead: (1) measure the observer's
    per-observation cost on a synthetic stage record, best-of-N
    (deterministic to ~nanoseconds); (2) count the REAL observations
    one workload pass generates; (3) time the pass, best-of-N. The
    overhead fraction = observations x per-obs cost / pass time. The
    budget is < 1% (override with DGRAPH_TPU_STATS_BUDGET);
    tools/check.sh gates on the exit code."""
    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_STATS_BUDGET", "0.01"))
    from dgraph_tpu.utils import coststore

    db, queries = _summary_mix()

    def one_pass() -> float:
        return _mix_pass_us(db, queries)

    # (1) per-observation cost of the observer, synthetic stage record
    store = coststore.store()
    rec_stage = {"name": "eq", "dur_us": 42.0, "trace_id": "bench",
                 "args": {"pred": "name", "n": 1000}}
    n_syn = 20_000
    per_obs_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_syn):
            store.observe_span(rec_stage)
        per_obs_us = min(per_obs_us,
                         (time.perf_counter_ns() - t0) / n_syn / 1e3)
    # (2) + (3) real observation volume and pass time
    for _ in range(2):
        one_pass()  # warm plans, column caches, stats caches
    before = coststore.stats()["observations"]
    pass_us = one_pass()
    obs_per_pass = coststore.stats()["observations"] - before
    for _ in range(runs - 1):
        pass_us = min(pass_us, one_pass())
    coststore.reset()
    frac = obs_per_pass * per_obs_us / pass_us if pass_us else 0.0
    rec = {"metric": "stats_overhead",
           "queries": len(queries),
           "pass_ms": round(pass_us / 1e3, 3),
           "observations_per_pass": int(obs_per_pass),
           "per_observation_us": round(per_obs_us, 4),
           "overhead_frac": round(frac, 5),
           "budget_frac": budget_frac,
           "within_budget": frac < budget_frac}
    print(json.dumps(rec))
    return rec


def planner_overhead_bench(runs: int = 5,
                           budget_frac: float = None) -> dict:
    """`--planner-overhead`: cost of the adaptive planner's per-stage
    tier decisions on the golden summary workload, decomposed like the
    stats/pprof/netfault gates (a sub-1% A/B cannot resolve through
    shared-runner scheduler noise):

      (1) per-CONSULT cost (a choose() that hits the plan's decision
          cache — the rebuild/cold path) and per-SERVE cost (the
          executor's warm _routed plan-layer probe — the steady
          state), each best-of-N on a real compiled plan;
      (2) consults AND warm serves per pass, counted by the planner
          on the real workload (warm passes consult zero times; the
          serves term is what keeps this gate meaningful then);
      (3) pass time, best-of-N.

    overhead fraction = (consults x per-consult + serves x per-serve)
    / pass time, budget < 1% (DGRAPH_TPU_PLANNER_BUDGET overrides).

    Doubles as the PLANNER SMOKE: after warm-up the workload must
    reach a pass that BUILDS zero new decisions — every stage served
    its tier from the plan cache (re-optimization may fire while
    estimates settle, so convergence is the assertion, not
    first-pass silence). Non-zero exit on either failure."""
    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_PLANNER_BUDGET", "0.01"))
    db, queries = _summary_mix()
    pl = getattr(db, "planner_impl", None)
    assert pl is not None, \
        "summary-mix engine must run the adaptive planner"

    # (1) per-consult (choose with a cached decision) and per-serve
    # (the executor's warm _routed probe, incl. the per-request memo
    # reset a fresh request implies) on a real compiled plan
    from dgraph_tpu.query.executor import Executor

    parsed, plan = db.plan_cache.lookup(
        db, '{ q(func: eq(name, "Movie 1")) { uid name } }', None)
    est = {"estRows": 64, "estRowsMax": 1024, "basis": "stats"}
    avail = ("postings", "columnar", "compressed")
    pl.choose(plan, "eq", "name", est, avail)  # build outside timing
    n_syn = 20_000
    per_consult_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_syn):
            pl.choose(plan, "eq", "name", est, avail)
        per_consult_us = min(per_consult_us,
                             (time.perf_counter_ns() - t0) / n_syn
                             / 1e3)
    ex = Executor(db, db.coordinator.max_assigned(), plan=plan)
    builder = (lambda: pl.choose(plan, "eq", "name", est, avail))
    ex._routed(("eq", "name", 1), builder)  # seed the routing layer
    per_serve_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_syn):
            ex._dec_memo.clear()  # a fresh request's plan-layer hit
            ex._routed(("eq", "name", 1), builder)
        per_serve_us = min(per_serve_us,
                           (time.perf_counter_ns() - t0) / n_syn
                           / 1e3)

    # (2)+(3) real consult volume, pass time, and the convergence
    # smoke: a pass that serves every decision from the plan cache
    def one_pass() -> float:
        return _mix_pass_us(db, queries)

    for _ in range(2):
        one_pass()  # warm plans, column caches, cost cells
    converged_pass = None
    builds_last = -1
    for i in range(10):
        before = pl.stats()
        one_pass()
        after = pl.stats()
        builds_last = after["decisions"] - before["decisions"]
        if builds_last == 0:
            converged_pass = i + 3  # incl. the 2 warm passes
            break
    before = pl.stats()
    pass_us = one_pass()
    after = pl.stats()
    consults = after["consults"] - before["consults"]
    serves = after["warmServes"] - before["warmServes"]
    for _ in range(runs - 1):
        pass_us = min(pass_us, one_pass())
    frac = (consults * per_consult_us + serves * per_serve_us) \
        / pass_us if pass_us else 0.0
    rec = {"metric": "planner_overhead",
           "queries": len(queries),
           "pass_ms": round(pass_us / 1e3, 3),
           "consults_per_pass": int(consults),
           "warm_serves_per_pass": int(serves),
           "per_consult_us": round(per_consult_us, 4),
           "per_serve_us": round(per_serve_us, 4),
           "overhead_frac": round(frac, 5),
           "budget_frac": budget_frac,
           "cache_converged_after_pass": converged_pass,
           "builds_in_last_checked_pass": builds_last,
           "within_budget": frac < budget_frac
           and converged_pass is not None}
    print(json.dumps(rec))
    return rec


def pprof_overhead_bench(runs: int = 5, threads: int = 12,
                         stack_depth: int = 24,
                         budget_frac: float = None) -> dict:
    """`--pprof-overhead`: cost of the on-demand sampling profiler
    (utils/pprof) at its default rate, against the ISSUE's < 2%
    throughput-impact budget.

    Methodology mirrors --stats-overhead: a differential A/B at a
    ~1% effect size cannot resolve through shared-runner scheduler
    noise, so the gate decomposes. Each sample holds the GIL for one
    sys._current_frames() walk over every live thread — the HELD-GIL
    walk is the throughput theft (nothing else runs meanwhile), so
    overhead fraction = DEFAULT_HZ x per-sample walk time.

    Recalibrated (was: 12 GIL-spinning busy threads): the old
    population made the tight measurement loop pay a GIL-ACQUISITION
    wait per iteration — up to a switch interval behind each spinning
    thread — and that wait is not theft (a worker thread runs during
    it; in production the 100 Hz sampler pays it while the server
    makes progress). On a contended box the wait dominated the walk
    ~8x and the gate failed at 2.4% while the actual steal was well
    under budget. The population is now `threads` ALIVE, DEEP-STACKED
    but BLOCKED threads (realistic frames to walk, zero GIL
    contention), so the loop times exactly the held-GIL walk the
    decomposition multiplies by DEFAULT_HZ. Budget override:
    DGRAPH_TPU_PPROF_BUDGET."""
    import threading

    from dgraph_tpu.utils import pprof

    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_PPROF_BUDGET", "0.02"))
    stop = threading.Event()
    ready = []
    ready_lock = threading.Lock()

    def parked(depth: int):
        # build a realistic stack for the walk, then block GIL-free
        if depth:
            parked(depth - 1)
            return
        with ready_lock:
            ready.append(1)
        stop.wait()

    pool = [threading.Thread(target=parked, args=(stack_depth,),
                             daemon=True)
            for _ in range(threads)]
    for t in pool:
        t.start()
    end = time.monotonic() + 10
    while time.monotonic() < end:
        with ready_lock:
            if len(ready) == threads:
                break
        time.sleep(0.005)
    try:
        me = frozenset({threading.get_ident()})
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        n = 2000
        per_sample_s = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter_ns()
            for _ in range(n):
                pprof.sample_once(me, names)
            per_sample_s = min(
                per_sample_s, (time.perf_counter_ns() - t0) / n / 1e9)
    finally:
        stop.set()
        for t in pool:
            t.join(timeout=2)
    frac = pprof.DEFAULT_HZ * per_sample_s
    rec = {"metric": "pprof_overhead",
           "hz": pprof.DEFAULT_HZ,
           "threads_sampled": threads,
           "stack_depth": stack_depth,
           "per_sample_us": round(per_sample_s * 1e6, 2),
           "overhead_frac": round(frac, 5),
           "budget_frac": budget_frac,
           "within_budget": frac < budget_frac}
    print(json.dumps(rec))
    return rec


def netfault_overhead_bench(runs: int = 5,
                            checks_per_op: int = 8,
                            budget_frac: float = None) -> dict:
    """`--netfault-overhead`: cost of the INERT network-fault seam
    (utils/netfault.py `armed()` — one falsy-dict check) on the wire
    hot paths, against the < 1% acceptance budget.

    Decomposed like the stats/pprof gates (a sub-1% A/B cannot
    resolve through scheduler noise): (1) the per-check cost of the
    disarmed seam, best-of-N over a tight loop; (2) a conservative
    nominal check count per served operation — one client _rpc_once
    plus the raft append+heartbeat sends a replicated write fans out
    (transport.send per peer), rounded UP to `checks_per_op`; (3) the
    per-query time of the golden summary mix (the same pass the stats
    gate times — the FASTEST ops the cluster serves, so the fraction
    is an upper bound: cluster ops also pay real network time these
    single-node queries don't). Budget override:
    DGRAPH_TPU_NETFAULT_BUDGET."""
    from dgraph_tpu.utils import netfault

    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_NETFAULT_BUDGET", "0.01"))
    assert not netfault.armed(), "gate must measure the INERT path"
    # (1) per-check cost, disarmed
    n_syn = 200_000
    per_check_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_syn):
            netfault.armed()
        per_check_us = min(per_check_us,
                           (time.perf_counter_ns() - t0) / n_syn / 1e3)
    # (3) per-query time on the summary mix (shared definition)
    db, queries = _summary_mix()
    for _ in range(2):
        _mix_pass_us(db, queries)  # warm plans and caches
    pass_us = min(_mix_pass_us(db, queries) for _ in range(runs))
    per_query_us = pass_us / max(1, len(queries))
    frac = checks_per_op * per_check_us / per_query_us
    rec = {"metric": "netfault_overhead",
           "queries": len(queries),
           "per_check_us": round(per_check_us, 5),
           "checks_per_op": checks_per_op,
           "per_query_us": round(per_query_us, 2),
           "overhead_frac": round(frac, 6),
           "budget_frac": budget_frac,
           "within_budget": frac < budget_frac}
    print(json.dumps(rec))
    return rec


def racecheck_overhead_bench(runs: int = 5,
                             accesses_per_op: int = 32,
                             budget_frac: float = None) -> dict:
    """`--racecheck-overhead`: cost of the ARMED attribute-access race
    witness (utils/racecheck) on the query hot path, against the < 5%
    acceptance budget the marked tier-1 concurrency suites run under.

    Decomposed like the stats/netfault gates (an A/B at this effect
    size cannot resolve through scheduler noise): (1) the per-sampled-
    access cost — armed minus unarmed tight loop over a registered
    probe class, best-of-N; (2) a conservative nominal sampled-access
    count per served operation — a MicroBatcher leader touches a few
    dozen witnessed attributes per query_json, rounded UP to
    `accesses_per_op` and max'd with the REAL sample count an armed
    batcher pass records; (3) the per-query time of the golden summary
    mix (the fastest ops served, so the fraction is an upper bound).
    Budget override: DGRAPH_TPU_RACECHECK_BUDGET."""
    from dgraph_tpu.engine.batcher import MicroBatcher
    from dgraph_tpu.utils import racecheck

    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_RACECHECK_BUDGET", "0.05"))

    class _Probe:
        def __init__(self):
            self.x = 0

    def spin(p, n):
        t0 = time.perf_counter_ns()
        for _ in range(n):
            p.x = p.x + 1  # one witnessed read + one witnessed write
        return (time.perf_counter_ns() - t0) / n / 1e3

    # (1) per-sampled-access delta: unarmed baseline vs armed probe
    n_syn = 50_000
    base_us = min(spin(_Probe(), n_syn) for _ in range(runs))
    racecheck.register(_Probe)
    racecheck.enable()
    try:
        armed_us = min(spin(_Probe(), n_syn) for _ in range(runs))
    finally:
        racecheck.disable()
    per_access_us = max(0.0, (armed_us - base_us) / 2)

    # (3) per-query time, unarmed (shared golden-mix definition)
    db, queries = _summary_mix()
    for _ in range(2):
        _mix_pass_us(db, queries)  # warm plans and caches
    pass_us = min(_mix_pass_us(db, queries) for _ in range(runs))
    per_query_us = pass_us / max(1, len(queries))

    # (2) real sampled-access volume of an armed batcher pass
    racecheck.enable()
    try:
        batcher = MicroBatcher(db, window_us=0)
        for q in queries:
            batcher.query_json(q)
        measured = racecheck.stats()["samples"] / max(1, len(queries))
    finally:
        racecheck.disable()
    per_op = max(accesses_per_op, int(measured) + 1)

    frac = per_op * per_access_us / per_query_us
    rec = {"metric": "racecheck_overhead",
           "queries": len(queries),
           "per_access_us": round(per_access_us, 5),
           "accesses_per_op": per_op,
           "measured_samples_per_op": round(measured, 2),
           "per_query_us": round(per_query_us, 2),
           "overhead_frac": round(frac, 6),
           "budget_frac": budget_frac,
           "within_budget": frac < budget_frac}
    print(json.dumps(rec))
    return rec


def watchdog_overhead_bench(runs: int = 5,
                            budget_frac: float = None) -> dict:
    """`--watchdog-overhead`: cost of the always-on alerting plane
    (utils/watchdog's evaluator tick + the per-request reqlog observer
    utils/alerts feeds its SLO windows with) against the < 1%
    acceptance budget.

    Decomposed like the stats/netfault gates (a sub-1% A/B cannot
    resolve through scheduler noise): (1) the per-tick cost of
    Watchdog.tick() on a WARM manager — every default rule loaded,
    SLO windows populated with op+tenant series, signal providers
    registered, healthy signal values so no rule fires — best-of-N;
    the evaluator runs once per tick_s, so its duty cycle is
    per_tick / tick_s; (2) the per-request cost of
    AlertManager.observe_request on a realistic reqlog record,
    best-of-N; (3) the per-query time of the golden summary mix (the
    fastest ops served, so the observer fraction is an upper bound).
    overhead = per_tick/(tick_s) + per_obs/per_query. Budget
    override: DGRAPH_TPU_WATCHDOG_BUDGET."""
    from dgraph_tpu.utils import alerts, watchdog

    if budget_frac is None:
        budget_frac = float(os.environ.get(
            "DGRAPH_TPU_WATCHDOG_BUDGET", "0.01"))
    tick_s = 1.0
    wd = watchdog.Watchdog(tick_s=tick_s,
                           manager=alerts.AlertManager())
    wd.register_signals("bench", lambda: {
        "raft_apply_lag": 3.0, "raft_peer_silent_s": 0.2,
        "cdc_max_lag": 1.0})
    rec_ok = {"op": "query", "outcome": "ok", "tenant": "t0"}
    for _ in range(2_000):
        wd.manager.observe_request(rec_ok)
    wd.tick()  # baseline tick: rate rules need a prev snapshot

    # (1) per-tick cost, warm manager, nothing firing
    n_ticks = 2_000
    per_tick_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_ticks):
            wd.tick()
        per_tick_us = min(
            per_tick_us, (time.perf_counter_ns() - t0) / n_ticks / 1e3)

    # (2) per-observation cost of the reqlog observer
    n_syn = 50_000
    per_obs_us = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter_ns()
        for _ in range(n_syn):
            wd.manager.observe_request(rec_ok)
        per_obs_us = min(
            per_obs_us, (time.perf_counter_ns() - t0) / n_syn / 1e3)

    # (3) per-query time on the summary mix (shared definition)
    db, queries = _summary_mix()
    for _ in range(2):
        _mix_pass_us(db, queries)  # warm plans and caches
    pass_us = min(_mix_pass_us(db, queries) for _ in range(runs))
    per_query_us = pass_us / max(1, len(queries))

    tick_frac = per_tick_us / (tick_s * 1e6)
    obs_frac = per_obs_us / per_query_us
    frac = tick_frac + obs_frac
    rec = {"metric": "watchdog_overhead",
           "queries": len(queries),
           "per_tick_us": round(per_tick_us, 3),
           "tick_s": tick_s,
           "tick_frac": round(tick_frac, 6),
           "per_observation_us": round(per_obs_us, 5),
           "per_query_us": round(per_query_us, 2),
           "observer_frac": round(obs_frac, 6),
           "overhead_frac": round(frac, 6),
           "budget_frac": budget_frac,
           "within_budget": frac < budget_frac}
    print(json.dumps(rec))
    return rec


def main():
    from dgraph_tpu.utils.backend import force_cpu_backend, probe_backend

    if "--lint-timing" in sys.argv:
        if not lint_timing_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--span-overhead" in sys.argv:
        span_overhead_bench()
        return
    if "--stats-overhead" in sys.argv:
        if not stats_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--planner-overhead" in sys.argv:
        if not planner_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--pprof-overhead" in sys.argv:
        if not pprof_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--netfault-overhead" in sys.argv:
        if not netfault_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--racecheck-overhead" in sys.argv:
        if not racecheck_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--watchdog-overhead" in sys.argv:
        if not watchdog_overhead_bench()["within_budget"]:
            sys.exit(1)
        return
    if "--setops-compressed" in sys.argv:
        if not setops_compressed_bench()["within_budget"]:
            sys.exit(1)
        return

    kway_bench()

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        force_cpu_backend()
    else:
        try:
            probe_backend(retries=3, backoff_s=5.0)
        except Exception:
            force_cpu_backend()
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops.uidvec import from_numpy, intersect, to_numpy

    platform = jax.devices()[0].platform
    results = []
    # K pairs per device call (vmap) — the engine's usage shape: one
    # batched call per query level, not one dispatch per pair (a lone
    # small kernel only measures tunnel round-trip latency)
    for n_a, ratio, overlap, k in [(1_000_000, 1, 0.3, 8),
                                   (65_536, 8, 0.1, 128),
                                   (16_384, 1, 0.3, 1024)]:
        pairs = [make_pair(n_a, ratio, overlap, seed=s)
                 for s in range(k)]
        sz_a = max(len(a) for a, _ in pairs)
        sz_b = max(len(b) for _, b in pairs)
        da = jax.device_put(jnp.stack(
            [from_numpy(a, size=1 << (sz_a - 1).bit_length())
             for a, _ in pairs]))
        db = jax.device_put(jnp.stack(
            [from_numpy(b, size=1 << (sz_b - 1).bit_length())
             for _, b in pairs]))

        t = time.perf_counter()
        want = [np.intersect1d(a, b, assume_unique=True)
                for a, b in pairs]
        cpu_s = time.perf_counter() - t

        fn = jax.jit(jax.vmap(intersect))
        out = np.asarray(fn(da, db))
        for i in range(k):
            assert np.array_equal(to_numpy(out[i]), want[i]), i
        # block_until_ready is unreliable over the remote-TPU tunnel
        # (returns before completion); a digest readback forces true
        # completion, and the measured empty-readback floor is
        # subtracted so only device time counts
        digest = jax.jit(
            lambda x, y: jnp.sum(jax.vmap(intersect)(x, y),
                                 dtype=jnp.uint32))
        floor_fn = jax.jit(lambda x: jnp.sum(x[:1, :8],
                                             dtype=jnp.uint32))
        np.asarray(digest(da, db))
        np.asarray(floor_fn(da))
        times, floors = [], []
        for _ in range(RUNS):
            t = time.perf_counter()
            np.asarray(floor_fn(da))
            floors.append(time.perf_counter() - t)
            t = time.perf_counter()
            np.asarray(digest(da, db))
            times.append(time.perf_counter() - t)
        dev_s = max(1e-6, float(np.median(times)) -
                    float(np.median(floors)))
        nbytes = (da.size + db.size) * 4
        rec = {"config": f"a={n_a} ratio={ratio} "
                         f"overlap={overlap} pairs={k}",
               "platform": platform,
               "device_gbps": round(nbytes / dev_s / 1e9, 2),
               "cpu_gbps": round(nbytes / cpu_s / 1e9, 2),
               "speedup": round(cpu_s / dev_s, 2)}
        results.append(rec)
        print(json.dumps(rec))
    best = max(r["device_gbps"] for r in results)
    print(json.dumps({"metric": "uid_intersect_gbps", "value": best,
                      "unit": "GB/s", "platform": platform}))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # structured failure, never a bare crash
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"metric": "uid_intersect_gbps", "value": None,
                          "error": f"{type(exc).__name__}: {exc}"}))
        sys.exit(0)
